"""Static overlap / critical-path analysis over compiled HLO (DSO7xx).

The comm and memory ledgers (PRs 7–8) price each compiled program's
wire bytes and HBM footprint; this module answers the *scheduling*
question the ledgers leave open: **which of those wire seconds are
exposed** — paid as step latency — and which are hidden behind
concurrent compute?  The reference's overlap machinery (ZeRO-Offload's
delayed parameter update, the pipeline engine's interleaved
comm/compute schedules) only pays off when overlap actually
materializes in the compiled program, and post-scheduling HLO makes
that statically decidable:

- a **sync collective** (``all-reduce`` with no ``-start/-done`` split)
  blocks its dependents by construction — its wire seconds are fully
  exposed, however much independent compute sits in the program;
- an **async pair** (``all-reduce-start``/``-done``,
  ``copy-start``/``copy-done``, ``send``/``recv``) hides wire behind
  whatever compute the scheduler placed between issue and completion
  (``is_scheduled=true`` modules print in schedule order, so "between"
  is the text order);
- the **streamed-offload host round trips** run *outside* any single
  program (device_put/device_get between dispatches), so the engine's
  own wire accounting (``host_state_bytes_per_step``) declares them —
  and absent async copy machinery in the update program they are
  serialized by construction (PERF.md's ~2× offload-tax accounting,
  now a per-program receipt instead of prose).

Per program this module computes: an instruction dependency graph
(extending the PR 8 collective parser with ``copy-start/copy-done``,
``send/recv`` and async ``-start/-done`` pairs), roofline node costs
(flops vs bytes over the chip tables in :mod:`.utilization`), the
**critical-path seconds**, a per-collective / per-transfer **overlap
classification** (``overlapped`` / ``partially_exposed`` /
``serialized``, each with the concurrent-compute window that could
hide it), and the ``exposed_wire_seconds`` / ``overlap_fraction``
summary the DSO7xx dslint rules, ``engine.verify_programs()``, the
capacity planner, and the bench receipts all quote.

Everything is a pure function of the HLO text plus static chip tables:
stdlib + regex only, zero device work — analysis happens at compile
(record) time or offline, never on the step path.  Costs are a *model*
(ring wire formulas, roofline min-bounds, while-body trip counts from
``known_trip_count`` when the backend prints them); the point is the
classification and the ratchetable exposure metric, not nanosecond
truth.
"""

import dataclasses
import re
from typing import Dict, List, Optional

from . import comm as comm_prof
from .utilization import chip_specs

OVERLAP_SCHEMA_VERSION = 1

# programs whose dispatch performs the offloaded optimizer update: the
# engine's DECLARED host-state stream (host_state_bytes_per_step —
# round trips that happen between dispatches, invisible in any one
# program's HLO) attaches to these and only these
UPDATE_PROGRAMS = ("train_step", "train_step_compressed", "apply_update")

# programs that carry (part of) the ZeRO-2 data-parallel gradient
# exchange: the engine's DECLARED collective schedule (overlap_comm
# bucket geometry) attaches to these and only these — the fused step
# holds both sides, the step-wise programs one each
EXCHANGE_PROGRAMS = ("train_step", "fwd_bwd", "apply_update",
                     "cast_params")

# the bucketed-exchange collective ops a declared schedule re-prices
# (the loss pmean / gnorm psum land as all-reduce and stay untouched)
_SCHEDULE_OPS = ("reduce-scatter", "all-gather")

# overlap classifications (per comm/transfer node)
OVERLAPPED = "overlapped"
PARTIAL = "partially_exposed"
SERIALIZED = "serialized"

# a node counts as fully overlapped when >= 95% of its wire seconds are
# hidden (scheduling jitter makes exact equality meaningless)
OVERLAP_SLACK = 0.05

# DSO701 fires only when a fully serialized collective has at least
# this much independent compute available to hide it — micro-programs
# (CPU-mesh CI runs, tiny fixtures) have nothing to overlap WITH, and
# flagging them would be noise
DSO701_MIN_WINDOW_SECONDS = 1e-3

# ancestor/descendant reachability is O(N^2/64) bitset work; beyond
# this instruction count the independent-compute windows degrade to
# "unknown" (None) rather than stalling a compile-time hook
MAX_WINDOW_INSTRUCTIONS = 20000

# instruction kinds carrying wire cost
KIND_COLLECTIVE = "collective"
KIND_HOST = "host_transfer"
KIND_P2P = "p2p_transfer"

# ops that route/alias but execute in ~zero time
_FREE_OPS = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id",
))

# one instruction: ``[ROOT] %name = <result type> <op>(operands)attrs``
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.+)$")
# first op token followed by an opening paren (the result type never
# contains ``word(``: shapes are ``f32[2,3]{1,0}`` and tuple types wrap
# shapes in parens without call syntax)
_OP_TOKEN_RE = re.compile(r"(?:^|\s)(?P<op>[a-z][a-z0-9\-]*)\(")
# computation header: ``[ENTRY] %name (params) -> type {``
_COMP_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*->.*\{\s*$")
_OPERAND_NAME_RE = re.compile(r"%(?P<name>[\w.\-]+)")
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|condition|body)=%?(?P<name>[\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{(?P<names>[^}]*)\}")
_TRIP_COUNT_RE = re.compile(r'"known_trip_count":\{"n":"(?P<n>\d+)"\}')
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{(?P<dims>[0-9,]*)\}")


@dataclasses.dataclass
class Instruction:
    name: str
    op: str
    outs: str          # result type text
    operands: str      # text between the op's parens
    attrs: str         # text after the operand close paren
    line: str
    index: int

    @property
    def is_start(self) -> bool:
        return self.op.endswith("-start") or self.op in ("send", "recv")

    @property
    def is_done(self) -> bool:
        return self.op.endswith("-done")


@dataclasses.dataclass
class Computation:
    name: str
    is_entry: bool
    instructions: List[Instruction]

    def __post_init__(self):
        self.by_name = {i.name: i for i in self.instructions}


def parse_hlo_computations(hlo_text: str):
    """``(computations, entry_name, scheduled)`` from one HLO module
    dump.  ``entry_name`` falls back to the last computation when no
    ENTRY marker is present (hand-written fixtures)."""
    comps: Dict[str, Computation] = {}
    entry_name = None
    current = None
    for raw in hlo_text.splitlines():
        line = raw.rstrip()
        if current is None:
            m = _COMP_RE.match(line.strip())
            if m and "=" not in line.split("(", 1)[0]:
                current = Computation(name=m.group("name"),
                                      is_entry=bool(m.group("entry")),
                                      instructions=[])
            continue
        if line.strip() == "}":
            current.__post_init__()
            comps[current.name] = current
            if current.is_entry:
                entry_name = current.name
            current = None
            continue
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        rest = m.group("rest")
        om = _OP_TOKEN_RE.search(rest)
        if om is None:
            continue
        op = om.group("op")
        outs = rest[:om.start()].strip()
        # operand region: from the op's paren to its matching close
        depth = 0
        start = om.end() - 1
        end = len(rest)
        for i in range(start, len(rest)):
            if rest[i] == "(":
                depth += 1
            elif rest[i] == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        current.instructions.append(Instruction(
            name=m.group("name"), op=op, outs=outs,
            operands=rest[start + 1:end], attrs=rest[end + 1:],
            line=line, index=len(current.instructions)))
    if entry_name is None and comps:
        entry_name = list(comps)[-1]
    scheduled = "is_scheduled=true" in hlo_text.split("\n", 1)[0]
    return comps, entry_name, scheduled


# ---------------------------------------------------------------------------
# host/p2p transfer parsing (the CommLedger satellite shares these)
# ---------------------------------------------------------------------------

# ``copy-start`` = an async copy; with a host memory-space annotation
# (``S(5)`` on TPU lowerings) it is a host<->device DMA.  ``send/recv``
# carry ``is_host_transfer=true`` for host streams, otherwise they are
# point-to-point device wire (pipeline stages).  ``-done`` halves never
# match (their ``-start``/``send``/``recv`` already counted).
# the result-tuple alternative admits one nesting level: memory-space
# layout annotations print parens inside the tuple (``{0:S(5)}``)
_TRANSFER_RE = re.compile(
    r"=\s*(?P<outs>\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
    r"(?P<op>copy-start|send|recv)\(")
_HOST_SPACE_RE = re.compile(r"S\(5\)|is_host_transfer=true")


def _largest_shape_bytes(text):
    sizes = comm_prof._shape_bytes_list(text)
    return max(sizes) if sizes else 0


def parse_hlo_transfers(hlo_text: str):
    """``[{op, bytes, host}]`` — one record per async transfer
    instruction (``copy-start``, ``send``, ``recv``) in an HLO module
    dump.  ``host`` marks host<->device transfers (host memory space
    ``S(5)`` or ``is_host_transfer=true``); the rest are device
    point-to-point wire.  Payload bytes are the LARGEST typed shape on
    the instruction (async results are bookkeeping tuples of operand
    alias + payload + context — summing would double-count)."""
    out = []
    for line in hlo_text.splitlines():
        m = _TRANSFER_RE.search(line)
        if m is None:
            continue
        n = _largest_shape_bytes(line)
        out.append({"op": m.group("op"), "bytes": n,
                    "host": bool(_HOST_SPACE_RE.search(line))})
    return out


def transfer_summary(transfers):
    """Aggregate parsed transfers into ledger-entry fields::

        {"host_transfers": N, "host_transfer_bytes": ...,
         "p2p_transfers": N, "p2p_transfer_bytes": ...}

    ``copy-start`` without a host memory space is a device-local async
    copy — neither bucket (it moves HBM, not wire)."""
    out = {"host_transfers": 0, "host_transfer_bytes": 0,
           "p2p_transfers": 0, "p2p_transfer_bytes": 0}
    for rec in transfers:
        if rec["host"]:
            out["host_transfers"] += 1
            out["host_transfer_bytes"] += rec["bytes"]
        elif rec["op"] in ("send", "recv"):
            out["p2p_transfers"] += 1
            out["p2p_transfer_bytes"] += rec["bytes"]
    return out


# ---------------------------------------------------------------------------
# roofline cost model
# ---------------------------------------------------------------------------

def _shape_elems(dims_text):
    n = 1
    for d in dims_text.split(","):
        if d:
            n *= int(d)
    return n


def _result_elems(outs):
    total = 0
    for m in comm_prof._SHAPE_RE.finditer(outs):
        total += _shape_elems(m.group("dims"))
    return total


def _dot_flops(ins):
    """2 * output elements * contracted extent, from the printed lhs
    shape + ``lhs_contracting_dims``; 0 when either is unparseable."""
    cm = _CONTRACT_RE.search(ins.attrs)
    lhs = comm_prof._SHAPE_RE.search(ins.operands)
    if cm is None or lhs is None:
        return 0
    dims = [int(x) for x in lhs.group("dims").split(",") if x]
    contracted = 1
    for i in (int(x) for x in cm.group("dims").split(",") if x):
        if i < len(dims):
            contracted *= dims[i]
    return 2 * _result_elems(ins.outs) * contracted


def _io_bytes(ins):
    sizes = comm_prof._shape_bytes_list(ins.operands)
    return sum(sizes) + sum(comm_prof._shape_bytes_list(ins.outs))


def _compute_cost(ins, specs, metrics):
    """Roofline seconds for one non-comm instruction: the larger of its
    flop time and its HBM-traffic time.  Call-like ops charge their
    callee (while bodies multiplied by ``known_trip_count`` when the
    backend printed one)."""
    op = ins.op
    if op in _FREE_OPS:
        return 0.0
    peak_flops = specs["peak_tflops"] * 1e12
    hbm_bps = specs["hbm_gbps"] * 1e9
    if op == "fusion":
        m = _CALLED_RE.search(ins.attrs)
        flops = metrics.get(m.group("name"), {}).get("flops", 0) if m else 0
        return max(flops / peak_flops, _io_bytes(ins) / hbm_bps)
    if op in ("call", "map"):
        m = _CALLED_RE.search(ins.attrs)
        return metrics.get(m.group("name"), {}).get("cp", 0.0) if m else 0.0
    if op == "while":
        trips = 1
        tm = _TRIP_COUNT_RE.search(ins.attrs)
        if tm:
            trips = max(int(tm.group("n")), 1)
        total = 0.0
        for cm in _CALLED_RE.finditer(ins.attrs):
            total += metrics.get(cm.group("name"), {}).get("cp", 0.0)
        return total * trips
    if op == "conditional":
        bm = _BRANCHES_RE.search(ins.attrs)
        if bm:
            branches = _OPERAND_NAME_RE.findall(bm.group("names"))
            return max([metrics.get(b, {}).get("cp", 0.0)
                        for b in branches] or [0.0])
        return 0.0
    if op == "dot":
        return max(_dot_flops(ins) / peak_flops, _io_bytes(ins) / hbm_bps)
    # element-wise / reductions / custom-calls: bytes dominate; charge
    # one flop per output element so pure-compute fixtures stay ordered
    return max(_result_elems(ins.outs) / peak_flops,
               _io_bytes(ins) / hbm_bps)


def _instr_flops(ins, metrics):
    """Flop count of one instruction (for fusion-body totals)."""
    if ins.op == "dot":
        return _dot_flops(ins)
    if ins.op in ("fusion", "call", "map", "while", "conditional"):
        total = 0
        for cm in _CALLED_RE.finditer(ins.attrs):
            total += metrics.get(cm.group("name"), {}).get("flops", 0)
        bm = _BRANCHES_RE.search(ins.attrs)
        if bm:
            for b in _OPERAND_NAME_RE.findall(bm.group("names")):
                total += metrics.get(b, {}).get("flops", 0)
        tm = _TRIP_COUNT_RE.search(ins.attrs)
        if tm:
            total *= max(int(tm.group("n")), 1)
        return total
    if ins.op in _FREE_OPS:
        return 0
    return _result_elems(ins.outs)


# ---------------------------------------------------------------------------
# per-computation analysis
# ---------------------------------------------------------------------------

def _wire_node(ins, specs, total_devices):
    """``(kind, wire_bytes, wire_seconds)`` when the instruction starts
    (or IS, for sync forms) a wire transfer; None otherwise."""
    base_op = ins.op[:-6] if ins.op.endswith("-start") else ins.op
    if base_op in comm_prof.COLLECTIVE_OPS:
        out_bytes = comm_prof._result_bytes(ins.outs,
                                            ins.op.endswith("-start"))
        group = comm_prof._group_size(ins.line, total_devices)
        wire = comm_prof.predicted_wire_bytes(base_op, out_bytes, group)
        return (KIND_COLLECTIVE, wire, wire / (specs["ici_gbps"] * 1e9))
    host = bool(_HOST_SPACE_RE.search(ins.line))
    if ins.op == "copy-start" and host:
        n = _largest_shape_bytes(ins.line)
        return (KIND_HOST, n, n / (specs["host_gbps"] * 1e9))
    if ins.op in ("send", "recv"):
        n = _largest_shape_bytes(ins.line)
        if host:
            return (KIND_HOST, n, n / (specs["host_gbps"] * 1e9))
        return (KIND_P2P, n, n / (specs["ici_gbps"] * 1e9))
    return None


def _independent_compute(comp, costs, node_indices):
    """{index: seconds of compute neither upstream nor downstream of
    the instruction} for the requested indices, via ancestor/descendant
    bitsets; None (unknown) past MAX_WINDOW_INSTRUCTIONS."""
    n = len(comp.instructions)
    if not node_indices:
        return {}
    if n > MAX_WINDOW_INSTRUCTIONS:
        return {i: None for i in node_indices}
    index_of = {ins.name: ins.index for ins in comp.instructions}
    deps = []
    for ins in comp.instructions:
        deps.append([index_of[nm] for nm in
                     _OPERAND_NAME_RE.findall(ins.operands)
                     if nm in index_of])
    anc = [0] * n
    for i in range(n):
        a = 0
        for d in deps[i]:
            a |= anc[d] | (1 << d)
        anc[i] = a
    desc = [0] * n
    for i in range(n - 1, -1, -1):
        di = desc[i] | (1 << i)
        for d in deps[i]:
            desc[d] |= di
    total = sum(costs)
    out = {}
    for i in node_indices:
        related = anc[i] | desc[i]
        dependent = 0.0
        j = 0
        while related:
            if related & 1:
                dependent += costs[j]
            related >>= 1
            j += 1
        out[i] = max(total - dependent, 0.0)
    return out


def _analyze_computation(comp, specs, metrics, total_devices, scheduled):
    """One computation's ``{cp, compute, flops, nodes}``: critical-path
    seconds (wire-aware), total roofline compute seconds, flop total,
    and the classified wire nodes."""
    finish: Dict[str, float] = {}
    issue: Dict[str, float] = {}     # -start name -> issue time
    pending: Dict[str, tuple] = {}   # -start name -> (kind, bytes, secs, idx)
    costs = [0.0] * len(comp.instructions)
    nodes = []
    compute_total = 0.0
    flops_total = 0
    for ins in comp.instructions:
        dep_t = 0.0
        for nm in _OPERAND_NAME_RE.findall(ins.operands):
            dep_t = max(dep_t, finish.get(nm, 0.0))
        flops_total += _instr_flops(ins, metrics)
        wire = _wire_node(ins, specs, total_devices)
        if ins.is_done:
            # completion of an async pair: no earlier than issue + wire
            started = [nm for nm in _OPERAND_NAME_RE.findall(ins.operands)
                       if nm in pending]
            t = dep_t
            for nm in started:
                kind, wbytes, wsecs, sidx = pending.pop(nm)
                t = max(t, issue.get(nm, 0.0) + wsecs)
                if kind == "copy":
                    # device-local async copy: HBM traffic, not wire —
                    # neither bucket, and its latency is schedule-hidden
                    # exactly like the wire pairs
                    continue
                # hidden window: compute scheduled between issue and
                # completion that does not depend on the start
                hidden = _async_hidden_window(comp, costs, sidx,
                                              ins.index, scheduled)
                nodes.append(_classify(ins_op=comp.instructions[sidx].op,
                                       kind=kind, wire_bytes=wbytes,
                                       seconds=wsecs, hidden=hidden,
                                       window=hidden, index=sidx,
                                       name=nm))
            finish[ins.name] = t
            continue
        if wire is not None and ins.is_start:
            issue[ins.name] = dep_t
            pending[ins.name] = (wire[0], wire[1], wire[2], ins.index)
            finish[ins.name] = dep_t  # issue is ~free
            continue
        if wire is not None:
            # sync form: blocks inline, fully exposed by construction
            kind, wbytes, wsecs = wire
            costs[ins.index] = 0.0
            nodes.append({"index": ins.index, "name": ins.name,
                          "op": ins.op, "kind": kind,
                          "wire_bytes": wbytes, "seconds": wsecs,
                          "hidden_seconds": 0.0, "window_seconds": None,
                          "classification": SERIALIZED, "source": "hlo"})
            finish[ins.name] = dep_t + wsecs
            continue
        cost = _compute_cost(ins, specs, metrics)
        if ins.op == "copy-start":
            # device-local async copy: charge HBM time at completion
            issue[ins.name] = dep_t
            pending[ins.name] = ("copy", 0, _io_bytes(ins) /
                                 (specs["hbm_gbps"] * 1e9), ins.index)
            finish[ins.name] = dep_t
            continue
        costs[ins.index] = cost
        compute_total += cost
        finish[ins.name] = dep_t + cost
    # any unmatched -start (malformed fixture): complete at the end
    for nm, (kind, wbytes, wsecs, sidx) in pending.items():
        if kind == "copy":
            continue
        nodes.append(_classify(ins_op=comp.instructions[sidx].op,
                               kind=kind, wire_bytes=wbytes, seconds=wsecs,
                               hidden=0.0, window=0.0, index=sidx, name=nm))
    # available-but-unused windows for the serialized nodes: sync forms
    # (window still None) and async pairs the scheduler left back-to-
    # back (achieved window 0) both get the DAG-independence window —
    # "what COULD have hidden this" is the DSO701/DSO702 message
    ser_idx = [n["index"] for n in nodes
               if n["classification"] == SERIALIZED and n["seconds"] > 0
               and not n["window_seconds"]]
    windows = _independent_compute(comp, costs, ser_idx)
    for node in nodes:
        if node["index"] in windows:
            node["window_seconds"] = windows[node["index"]]
    cp = max(finish.values(), default=0.0)
    return {"cp": cp, "compute": compute_total, "flops": flops_total,
            "nodes": nodes}


def _async_hidden_window(comp, costs, start_idx, done_idx, scheduled):
    """Compute seconds the scheduler placed between an async pair's
    issue and completion that do NOT depend on the start — what
    actually hides the wire.  Only meaningful for scheduled modules
    (text order == schedule order); unscheduled fixtures get the same
    slice-based estimate (the scheduler is free to realize it)."""
    del scheduled  # same estimate either way; kept for the signature
    start_name = comp.instructions[start_idx].name
    depends = {start_name}
    hidden = 0.0
    for ins in comp.instructions[start_idx + 1:done_idx]:
        names = set(_OPERAND_NAME_RE.findall(ins.operands))
        if names & depends:
            depends.add(ins.name)
            continue
        hidden += costs[ins.index]
    return hidden


def _classify(ins_op, kind, wire_bytes, seconds, hidden, window, index,
              name, source="hlo"):
    hidden = min(max(hidden, 0.0), seconds)
    if seconds <= 0:
        cls = OVERLAPPED
    elif hidden >= seconds * (1.0 - OVERLAP_SLACK):
        cls = OVERLAPPED
    elif hidden > 0:
        cls = PARTIAL
    else:
        cls = SERIALIZED
    base_op = ins_op[:-6] if ins_op.endswith("-start") else ins_op
    return {"index": index, "name": name, "op": base_op, "kind": kind,
            "wire_bytes": wire_bytes, "seconds": seconds,
            "hidden_seconds": hidden, "window_seconds": window,
            "classification": cls, "source": source}


# ---------------------------------------------------------------------------
# whole-program analysis
# ---------------------------------------------------------------------------

def _bucket(nodes, kind):
    sel = [n for n in nodes if n["kind"] == kind]
    return {"total": len(sel),
            "overlapped": sum(1 for n in sel
                              if n["classification"] == OVERLAPPED),
            "partially_exposed": sum(1 for n in sel
                                     if n["classification"] == PARTIAL),
            "serialized": sum(1 for n in sel
                              if n["classification"] == SERIALIZED)}


def _declared_stream_nodes(declared_residual, schedule, compute_total,
                           specs, hlo_excess_bytes=0):
    """Model the engine-declared between-dispatch host stream as wire
    nodes, honoring the declared ISSUE SCHEDULE.

    Serialized (no schedule, ``overlap: false``, or a single chunk):
    one fully exposed host transfer — the stream chains fetch → update
    → write-back per chunk, so every wire second is step latency
    (PERF.md's ~2× offload-tax accounting).

    Pipelined (``overlap: true, chunks: n, prefetch_depth: d``): the
    double-buffered schedule issues chunk *k+1*'s fetch and chunk *k*'s
    write-back concurrently with chunk *k*'s update, so the steady-state
    wire hides behind compute and only the pipeline FILL (first fetch)
    and DRAIN (last write-back) — one chunk's round trip, ``wire/n`` —
    plus whatever steady-state wire exceeds the available compute stays
    exposed.  Components share one compute budget (``compute_total``):
    seconds of compute can hide at most themselves, so a second
    declared component (the gradient spill/reload stream) draws from
    what the first left — the model never claims more hiding than the
    program holds.  ``hlo_excess_bytes`` is HLO-accounted host wire
    beyond the state declaration (TPU lowerings can materialize the
    grad spill as real transfer ops): it reduces the declared grad
    component the same way ``hlo_host_bytes`` reduces the state one,
    so no byte is ever counted both as an HLO node and as declared.
    """
    schedule = schedule or {}
    chunks = int(schedule.get("chunks") or 0)
    pipelined = bool(schedule.get("overlap")) and chunks > 1
    components = []
    if declared_residual > 0:
        components.append(("<declared-host-stream>", "host-stream",
                           declared_residual,
                           int(schedule.get("redundant_prefetch_chunks")
                               or 0)))
    grad_bytes = max(int(schedule.get("grad_wire_bytes") or 0)
                     - max(int(hlo_excess_bytes or 0), 0), 0)
    if grad_bytes > 0:
        components.append(("<declared-grad-stream>", "grad-stream",
                           grad_bytes, 0))
    nodes = []
    budget = max(float(compute_total), 0.0)
    bw = specs["host_gbps"] * 1e9
    for i, (name, op, nbytes, redundant) in enumerate(components):
        secs = nbytes / bw
        extra = (redundant * (nbytes / (2 * chunks)) / bw
                 if pipelined and chunks else 0.0)
        if not pipelined:
            hidden = 0.0
        else:
            fill_drain = secs / chunks
            hidden = min(max(secs - fill_drain, 0.0), budget)
            budget -= hidden
        nodes.append(_classify(
            ins_op=op, kind=KIND_HOST, wire_bytes=nbytes + int(
                extra * bw), seconds=secs + extra, hidden=hidden,
            window=compute_total, index=-(i + 1), name=name,
            source="declared"))
    return nodes


def _apply_collective_schedule(nodes, schedule, compute_total):
    """Re-price the bucketed ZeRO-2 gradient exchange per the engine's
    DECLARED collective schedule (``{overlap, rs_buckets, ag_buckets,
    ...}``).

    The CPU-mesh HLO shows only sync reduce-scatter / all-gather
    instructions — no ``-start/-done`` machinery — so text-order
    classification reads every bucket as serialized even though the
    bucketed program's data dependencies are real (bucket *i*'s
    reduce-scatter depends only on its leaves' backward; TPU's
    latency-hiding scheduler overlaps them).  Like the PR 12 declared
    host stream, the engine declares the schedule it built and this
    prices it:

    - ``overlap: true`` — steady-state buckets hide up to the
      independent-compute window (each node's DAG window when known,
      all sharing one ``compute_total`` budget — the model never claims
      more hiding than the program holds), and the pipeline FILL/DRAIN
      (one bucket's wire, ``W/B``) stays exposed.  Hiding is granted in
      issue order so the drain-side nodes keep the exposure.
    - ``overlap: false`` (the serialized control) — nothing hides, but
      the matching nodes' windows record the POTENTIAL window
      (``compute_total * (B-1)/B`` over the declared bucket count):
      what the bucketed schedule COULD have hidden.  That is the
      DSO701 message, and the reason the control trips it while the
      overlapped program verifies clean.

    Only sync HLO reduce-scatter/all-gather collective nodes are
    touched (``source`` becomes ``hlo+declared``); all-reduces (loss
    pmean, gnorm psum) and every transfer node keep their HLO-derived
    classification."""
    if not schedule:
        return
    matching = [n for n in nodes
                if n["kind"] == KIND_COLLECTIVE
                and n["op"] in _SCHEDULE_OPS
                and n["source"] == "hlo"]
    if not matching:
        return
    n_declared = (int(schedule.get("rs_buckets") or 0)
                  + int(schedule.get("ag_buckets") or 0))
    if not schedule.get("overlap"):
        if n_declared > 1:
            potential = max(
                float(compute_total) * (n_declared - 1) / n_declared,
                0.0)
            for n in matching:
                n["window_seconds"] = max(
                    float(n.get("window_seconds") or 0.0), potential)
                n["source"] = "hlo+declared"
        return
    B = len(matching)
    if B <= 1:
        return
    total = sum(n["seconds"] for n in matching)
    fill_drain = total / B
    budget = max(float(compute_total), 0.0)
    remaining = min(max(total - fill_drain, 0.0), budget)
    for n in sorted(matching, key=lambda x: x["index"]):
        cap = n.get("window_seconds")
        grant = remaining if cap is None else min(remaining,
                                                 max(float(cap), 0.0))
        hidden = min(n["seconds"], grant)
        remaining -= hidden
        re = _classify(ins_op=n["op"], kind=n["kind"],
                       wire_bytes=n["wire_bytes"], seconds=n["seconds"],
                       hidden=hidden,
                       window=(cap if cap is not None else budget),
                       index=n["index"], name=n["name"],
                       source="hlo+declared")
        n.update(re)


def analyze_hlo(hlo_text, total_devices=1, device_kind="",
                declared_host_wire_bytes=0, max_nodes=32,
                declared_host_stream=None,
                declared_collective_schedule=None):
    """Full overlap analysis of one compiled program.

    ``max_nodes`` caps the emitted per-node list (telemetry events must
    not balloon on collective-heavy programs; the bucket counts and
    second totals always cover EVERY node).  Pass ``max_nodes=None``
    for the untruncated list — the DSO7xx rule checks need every node,
    not the first 32.

    Returns the summary dict (schema below) or None when the text holds
    no parseable computation.  ``declared_host_wire_bytes`` is the
    engine-declared per-step host-state stream (see
    :data:`UPDATE_PROGRAMS`); the portion not accounted for by HLO-level
    transfer ops is modeled per the engine's declared issue schedule
    (``declared_host_stream``, :func:`_declared_stream_nodes`): one
    fully serialized host transfer absent a pipelined schedule, a
    fill/drain-exposed pipelined transfer under the double-buffered
    schedule the round-12 overlapped streaming builds.

    Known floor: wire nodes inside called computations (a collective in
    a ``while`` body) enter the node list and wire totals ONCE, while
    the critical path charges the body cost (wire included) times its
    ``known_trip_count`` — per-iteration wire totals would need the
    call-multiplicity product, which this model deliberately keeps
    simple.  This repo's step programs emit collectives at entry level
    (GSPMD), so the floor is theoretical today.

    Summary::

        {"overlap_schema_version", "device_kind", "scheduled",
         "instructions", "critical_path_seconds", "compute_seconds",
         "wire_seconds", "exposed_wire_seconds", "overlap_fraction",
         "collectives": {total, overlapped, partially_exposed,
                         serialized},
         "host_transfers": {...}, "p2p_transfers": {...},
         "nodes": [...], "nodes_truncated": N}
    """
    comps, entry_name, scheduled = parse_hlo_computations(hlo_text)
    if not comps or entry_name is None:
        return None
    specs = chip_specs(device_kind)
    metrics: Dict[str, dict] = {}
    nodes = []
    n_instructions = 0
    # computations print callees-first; one pass memoizes cleanly
    for name, comp in comps.items():
        m = _analyze_computation(comp, specs, metrics, total_devices,
                                 scheduled)
        metrics[name] = m
        nodes.extend(m["nodes"])
        n_instructions += len(comp.instructions)
    # program compute = the ENTRY computation's total: its call-like
    # instruction costs already fold their callees in (fusion bodies,
    # while cond+body x trip count) — summing every computation as well
    # would double-count each called body
    compute_total = metrics[entry_name]["compute"]
    cp = metrics[entry_name]["cp"]
    # HLO-visible transfer accounting over the SAME node set the
    # residual subtraction below uses — the CommLedger's
    # host_transfer_bytes entry fields derive from this (one
    # classification, not two walks that can desync)
    hlo_transfers = {
        "host_transfers": sum(1 for n in nodes
                              if n["kind"] == KIND_HOST),
        "host_transfer_bytes": sum(n["wire_bytes"] for n in nodes
                                   if n["kind"] == KIND_HOST),
        "p2p_transfers": sum(1 for n in nodes
                             if n["kind"] == KIND_P2P),
        "p2p_transfer_bytes": sum(n["wire_bytes"] for n in nodes
                                  if n["kind"] == KIND_P2P),
    }
    hlo_host_bytes = hlo_transfers["host_transfer_bytes"]
    declared_state = int(declared_host_wire_bytes or 0)
    declared_residual = max(declared_state - hlo_host_bytes, 0)
    nodes.extend(_declared_stream_nodes(
        declared_residual, declared_host_stream, compute_total, specs,
        hlo_excess_bytes=max(hlo_host_bytes - declared_state, 0)))
    # declared bucketed-collective schedule (overlap_comm): re-price
    # the HLO exchange nodes per the engine-declared issue schedule
    _apply_collective_schedule(nodes, declared_collective_schedule,
                               compute_total)
    wire = sum(n["seconds"] for n in nodes)
    exposed = sum(n["seconds"] - n["hidden_seconds"] for n in nodes)
    # per-kind exposed split over the FULL node set (the attribution
    # model's phase table needs "exposed collective wire" apart from
    # "exposed host stream", and the truncated per-node list below
    # cannot reconstruct it)
    exposed_by_kind = {KIND_COLLECTIVE: 0.0, KIND_HOST: 0.0,
                       KIND_P2P: 0.0}
    for n in nodes:
        exposed_by_kind[n["kind"]] += n["seconds"] - n["hidden_seconds"]
    summary = {
        "overlap_schema_version": OVERLAP_SCHEMA_VERSION,
        "device_kind": specs["device_kind"],
        "scheduled": scheduled,
        "instructions": n_instructions,
        "critical_path_seconds": cp,
        "compute_seconds": compute_total,
        "wire_seconds": wire,
        "exposed_wire_seconds": exposed,
        "exposed_by_kind": exposed_by_kind,
        "overlap_fraction": (1.0 - exposed / wire) if wire > 0 else 1.0,
        "collectives": _bucket(nodes, KIND_COLLECTIVE),
        "host_transfers": _bucket(nodes, KIND_HOST),
        "p2p_transfers": _bucket(nodes, KIND_P2P),
        "hlo_transfer_summary": hlo_transfers,
        "nodes": nodes if max_nodes is None else nodes[:max_nodes],
        "nodes_truncated": (0 if max_nodes is None
                            else max(len(nodes) - max_nodes, 0)),
    }
    return summary
