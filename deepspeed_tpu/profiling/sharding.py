"""Static sharding auditor: per-tensor HLO layouts → per-device
resident bytes, reconciled against the engine-DECLARED sharding spec.

The substrate of the DSS8xx rule family (``tools/dslint/programs.py``).
GSPMD writes the layout it actually materialized into the optimized
HLO as ``sharding={...}`` annotations on the entry computation's
parameters — ``{replicated}``, ``{devices=[2,1,2]<=[4]
last_tile_dim_replicate}`` tile assignments, or ``{maximal device=N}``.
This module parses those annotations (reusing the PR 8/11 parser
infrastructure: :func:`overlap.parse_hlo_computations` for the
instruction walk, ``comm``'s dtype/shape tables for byte math) into a
per-tensor layout map, prices **per-device resident bytes by family**
(params, master, optimizer state, KV cache, activations at the entry
boundary), and reconciles the result against the spec the engine
declared — the same mesh/PartitionSpec tuples its jits were built
with, carried in ``program_verify_context()["declared_sharding"]`` and
the ``<run_dir>/programs/`` sidecars.

Why this exists now: ROADMAP item 2 (parameter sharding past the
paper's ZeRO-2 ceiling) is only a capacity win if the ÷dp actually
MATERIALIZES.  A stage-3 step whose parameters compile replicated
trains correctly, benchmarks plausibly, and silently pays ×dp memory —
the same finite-loss silence as the PR 8 flatten replica-sum bug.  The
auditor makes that a static CI failure (DSS801) and a planner/bench
receipt (``param_bytes_per_device``) before stage 3 lands.

Like the rest of the profiling parsers this is stdlib+regex only — no
jax import — so dslint can borrow it lazily (and says "UNVERIFIED"
loudly via DSS804 when it cannot, the DSP614 contract).
"""

import re
from typing import Dict, List, Optional

from . import comm as comm_prof
from . import overlap as overlap_prof

# a declared-sharded tensor smaller than this cannot meaningfully fold
# memory: DSS801 stays quiet below it (CI fixtures are MiB-scale; the
# stage-3 tensors this rule guards are GiB-scale)
MIN_AUDIT_BYTES = 1 << 20

# family reconciliation order: the step-level state families first so
# a byte-size collision between a declared family and a stray entry
# tensor resolves toward the declared state
_FAMILY_ORDER = ("params", "master", "optimizer", "kv_cache")

_SHARDING_ATTR_RE = re.compile(r"sharding=\{(?P<body>[^}]*)\}")
_TILE_RE = re.compile(r"devices=\[(?P<dims>[0-9,]+)\]")
# boundary resharding collectives a producer/consumer layout mismatch
# lowers to (the DSS802 evidence); ``-done`` halves never match
_RESHARD_RE = re.compile(r"\b(?:all-to-all|collective-permute)(?:-start)?\(")


def parse_sharding_attr(attr_text: str) -> Optional[dict]:
    """One instruction's ``sharding={...}`` annotation →
    ``{kind, tile, divisor}``; None when the instruction carries no
    annotation (single-device modules annotate nothing).

    ``divisor`` is the number of distinct shards the tensor is split
    into — the per-device resident bytes are ``global_bytes //
    divisor``.  A ``last_tile_dim_replicate`` factor replicates shards
    and does not divide residency; ``{replicated}`` and
    ``{maximal device=N}`` both resolve to divisor 1 (maximal puts the
    WHOLE tensor on one device)."""
    m = _SHARDING_ATTR_RE.search(attr_text)
    if m is None:
        return None
    body = m.group("body")
    if "replicated" in body:
        return {"kind": "replicated", "tile": [], "divisor": 1}
    if "maximal" in body:
        return {"kind": "maximal", "tile": [], "divisor": 1}
    tm = _TILE_RE.search(body)
    if tm is None:
        return {"kind": "unknown", "tile": [], "divisor": 1}
    dims = [int(d) for d in tm.group("dims").split(",") if d]
    split = dims[:-1] if "last_tile_dim_replicate" in body else dims
    divisor = 1
    for d in split:
        divisor *= max(int(d), 1)
    return {"kind": "devices", "tile": dims, "divisor": max(divisor, 1)}


def entry_parameters(hlo_text: str) -> Optional[List[dict]]:
    """Per-tensor layout map of the entry computation's parameters:
    ``[{name, param, local_bytes, global_bytes, divisor, kind}]``.
    None when the text holds no computation (header-only artifact).

    Shapes in a partitioned module print LOCAL (per-shard); the global
    footprint is ``local_bytes × divisor``.  Tuple-shaped parameters
    (no single shape literal) are skipped — XLA's default pytree
    lowering flattens every leaf to its own parameter."""
    comps, entry_name, _ = overlap_prof.parse_hlo_computations(hlo_text)
    if entry_name is None or entry_name not in comps:
        return None
    out = []
    for instr in comps[entry_name].instructions:
        if instr.op != "parameter":
            continue
        shapes = comm_prof._shape_bytes_list(instr.outs)
        if len(shapes) != 1:
            continue
        sharding = parse_sharding_attr(instr.attrs)
        divisor = sharding["divisor"] if sharding else 1
        try:
            param_no = int(instr.operands.strip())
        except ValueError:
            param_no = -1
        out.append({
            "name": instr.name,
            "param": param_no,
            "local_bytes": int(shapes[0]),
            "global_bytes": int(shapes[0]) * divisor,
            "divisor": int(divisor),
            "kind": sharding["kind"] if sharding else "unannotated",
        })
    return out


def count_reshard_ops(hlo_text: str) -> int:
    """Boundary-reshard collective count (all-to-all /
    collective-permute, sync or ``-start`` async form) in one module —
    the DSS802 supporting evidence."""
    return len(_RESHARD_RE.findall(hlo_text))


# ---------------------------------------------------------------------------
# declared-spec helpers (engine side builds with these; no jax here)
# ---------------------------------------------------------------------------

def spec_axes_and_divisor(spec, mesh_axes: Dict[str, int]):
    """``(axis names, shard divisor)`` of one PartitionSpec-like value
    (an iterable of axis names / None / nested tuples) against the mesh
    axis sizes — exactly how GSPMD divides the tensor."""
    axes = []
    for entry in (spec or ()):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(str(a) for a in entry)
        else:
            axes.append(str(entry))
    divisor = 1
    for a in axes:
        divisor *= max(int(mesh_axes.get(a, 1)), 1)
    return axes, max(divisor, 1)


def build_declared_family(leaf_entries) -> dict:
    """One declared family from ``(global_bytes, axes, divisor)``
    leaf tuples — the sidecar-serializable shape the reconciler
    consumes."""
    leaves = [{"bytes": int(b), "axes": [str(a) for a in axes],
               "divisor": max(int(divisor), 1)}
              for b, axes, divisor in leaf_entries if int(b) > 0]
    return {"leaves": leaves,
            "total_bytes": sum(leaf["bytes"] for leaf in leaves)}


# ---------------------------------------------------------------------------
# reconciliation: declared spec vs materialized entry layout
# ---------------------------------------------------------------------------

def _family_sort_key(name):
    try:
        return (_FAMILY_ORDER.index(name), name)
    except ValueError:
        return (len(_FAMILY_ORDER), name)


def analyze_sharding(hlo_text: str,
                     declared: Optional[dict] = None) -> Optional[dict]:
    """The full sharding summary of one program: the entry layout map,
    per-family per-device resident bytes, and — when a declared spec is
    given — the declared-vs-materialized mismatches DSS801 fires on.

    Matching is greedy largest-first on EXACT global bytes within each
    family (preferring an entry tensor whose divisor agrees, so
    same-sized families — fp32 master vs Adam moments — never
    cross-claim a mismatch).  Entry parameters no family claims are the
    ``activations`` residue: the batch/scalar/carry tensors resident at
    the program boundary."""
    params = entry_parameters(hlo_text)
    if params is None:
        return None
    by_bytes: Dict[int, List[int]] = {}
    for i, p in enumerate(params):
        by_bytes.setdefault(p["global_bytes"], []).append(i)
    unmatched = set(range(len(params)))

    families = {}
    declared_families = (declared or {}).get("families") or {}
    if not isinstance(declared_families, dict):
        declared_families = {}
    for fam in sorted(declared_families, key=_family_sort_key):
        spec = declared_families.get(fam) or {}
        leaves = spec.get("leaves") if isinstance(spec, dict) else None
        leaves = [l for l in (leaves or []) if isinstance(l, dict)]
        matched = per_dev = declared_per_dev = unclaimed = 0
        div_bytes: Dict[int, int] = {}
        mismatches = []
        for leaf in sorted(leaves,
                           key=lambda l: -int(l.get("bytes") or 0)):
            b = int(leaf.get("bytes") or 0)
            if b <= 0:
                continue
            ddiv = max(int(leaf.get("divisor") or 1), 1)
            declared_per_dev += b // ddiv
            cand = [i for i in by_bytes.get(b, ()) if i in unmatched]
            if not cand:
                unclaimed += b
                continue
            pick = next((i for i in cand
                         if params[i]["divisor"] == ddiv), cand[0])
            unmatched.discard(pick)
            mdiv = max(params[pick]["divisor"], 1)
            matched += b
            per_dev += b // mdiv
            div_bytes[mdiv] = div_bytes.get(mdiv, 0) + b
            if mdiv < ddiv:
                mismatches.append({
                    "bytes": b,
                    "declared_divisor": ddiv,
                    "materialized_divisor": mdiv,
                    "axes": [str(a) for a in (leaf.get("axes") or [])],
                    "param": params[pick]["name"],
                })
        families[fam] = {
            "declared_bytes": sum(int(l.get("bytes") or 0)
                                  for l in leaves),
            "matched_bytes": matched,
            "unmatched_declared_bytes": unclaimed,
            "per_device_bytes": per_dev,
            "declared_per_device_bytes": declared_per_dev,
            # bytes-weighted dominant materialized divisor (the DSS802
            # cross-program consistency figure); None = nothing matched
            "materialized_divisor": (
                max(div_bytes, key=lambda d: (div_bytes[d], -d))
                if div_bytes else None),
            "mismatches": mismatches,
        }

    activation_bytes = sum(params[i]["local_bytes"] for i in unmatched)
    param_fam = families.get("params")
    return {
        "entry_parameters": len(params),
        "parameters": params,
        "families": families,
        "activation_bytes_per_device": int(activation_bytes),
        "param_bytes_per_device": (
            int(param_fam["per_device_bytes"])
            if param_fam and param_fam["matched_bytes"] else None),
        "param_bytes_global": (
            int(param_fam["matched_bytes"])
            if param_fam and param_fam["matched_bytes"] else None),
        "param_shard_divisor": (
            int(param_fam["materialized_divisor"])
            if param_fam and param_fam["materialized_divisor"] else None),
        "reshard_ops": count_reshard_ops(hlo_text),
    }
