from .profiler import (FlopsProfile, FlopsProfiler, backend_cost_analysis,
                       count_fn_flops, count_jaxpr_flops, get_model_profile,
                       params_count)

__all__ = ["FlopsProfile", "FlopsProfiler", "backend_cost_analysis",
           "count_fn_flops", "count_jaxpr_flops", "get_model_profile",
           "params_count"]
