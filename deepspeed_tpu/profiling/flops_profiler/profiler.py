"""Flops profiler: analytic jaxpr cost analysis + per-scope breakdown.

TPU-native re-design of the reference flops profiler
(``deepspeed/profiling/flops_profiler/profiler.py:11-814``).  The reference
monkey-patches ``torch.nn.functional`` and installs module hooks to count
MACs at runtime; under JAX the whole computation is available *statically*
as a jaxpr, so the profiler

- walks the jaxpr (through ``pjit``/``scan``/``cond``/``remat`` inner
  jaxprs, multiplying scan bodies by their trip count) counting matmul /
  conv / elementwise FLOPs analytically,
- attributes them to ``jax.named_scope`` paths (the analog of the
  reference's per-module table; models in ``deepspeed_tpu.models`` name
  their layers), and
- cross-checks against the backend's compiled cost analysis when the
  platform provides one (``Compiled.cost_analysis()``).

Profiling the *training* step needs no 3x heuristic: tracing
``value_and_grad`` (or the engine's fused step) yields the backward ops in
the jaxpr and they are counted exactly.
"""

from collections import defaultdict

import jax
import numpy as np

from ...utils.logging import logger


def _aval_size(aval):
    return int(np.prod(aval.shape)) if aval.shape else 1


def _dot_general_flops(eqn):
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = int(np.prod([lhs.shape[d] for d in lb])) if lb else 1
    contract = int(np.prod([lhs.shape[d] for d in lc])) if lc else 1
    lhs_free = _aval_size(lhs) // max(batch * contract, 1)
    rhs_free = _aval_size(rhs) // max(batch * contract, 1)
    return 2 * batch * lhs_free * rhs_free * contract


def _conv_flops(eqn):
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel
    # 2 * output elements * kernel elements per output channel
    dn = eqn.params["dimension_numbers"]
    out_feature_dim = dn.out_spec[1]
    kernel_size = _aval_size(rhs) // max(out.shape[out_feature_dim], 1)
    return 2 * _aval_size(out) * kernel_size


# elementwise / reduction primitives counted as one op per output element
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "exp", "log", "tanh", "pow",
    "rsqrt", "sqrt", "neg", "logistic", "erf", "integer_pow", "and", "or",
    "xor", "select_n",
}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "argmax", "argmin"}


def count_jaxpr_flops(jaxpr, by_scope=None, scale=1):
    """FLOPs of one execution of a jaxpr.  ``by_scope`` (optional dict)
    accumulates per-``named_scope`` totals, pre-multiplied by ``scale`` (the
    product of enclosing loop trip counts)."""
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "scan":
            length = int(eqn.params.get("length", 1))
            total += length * count_jaxpr_flops(
                eqn.params["jaxpr"].jaxpr, by_scope, scale * length)
            continue
        if prim == "cond":
            branches = eqn.params["branches"]
            if not branches:
                continue
            counts = [count_jaxpr_flops(b.jaxpr, None, scale) for b in branches]
            hot = int(np.argmax(counts))
            if by_scope is not None:
                count_jaxpr_flops(branches[hot].jaxpr, by_scope, scale)
            total += counts[hot]
            continue
        if prim == "while":
            # trip count is data-dependent: count one iteration (caveat
            # matches the reference's inability to see dynamic loops)
            total += count_jaxpr_flops(eqn.params["body_jaxpr"].jaxpr,
                                       by_scope, scale)
            continue
        inner = None
        for key in ("jaxpr", "call_jaxpr"):
            if key in eqn.params:
                inner = eqn.params[key]
                inner = getattr(inner, "jaxpr", inner)
                break
        if inner is not None:
            total += count_jaxpr_flops(inner, by_scope, scale)
            continue

        if prim == "dot_general":
            sub = _dot_general_flops(eqn)
        elif prim == "conv_general_dilated":
            sub = _conv_flops(eqn)
        elif prim in _ELEMENTWISE:
            sub = _aval_size(eqn.outvars[0].aval)
        elif prim in _REDUCE:
            sub = _aval_size(eqn.invars[0].aval)
        else:
            continue
        total += sub
        if by_scope is not None and sub:
            scope = str(eqn.source_info.name_stack) or "<top>"
            by_scope[scope] += sub * scale
    return total


def count_fn_flops(fn, *args, by_scope=None, **kwargs):
    """FLOPs of ``fn(*args, **kwargs)`` (fn may be jitted — tracing goes
    through).  Returns (flops, by_scope or None)."""
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    scope = defaultdict(int) if by_scope is None else by_scope
    flops = count_jaxpr_flops(closed.jaxpr, scope)
    return flops, dict(scope)


def params_count(params):
    return int(sum(_aval_size(x) for x in jax.tree_util.tree_leaves(params)))


def backend_cost_analysis(jitted_fn, *args, **kwargs):
    """The compiled executable's own cost model, where the backend provides
    one (flops, bytes accessed).  Returns {} when unavailable."""
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost or {})
    except Exception as e:  # pragma: no cover - backend specific
        logger.debug(f"backend cost analysis unavailable: {e}")
        return {}


def _fmt(n):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n:.0f} "


def get_model_profile(model=None, batch=None, params=None, fn=None, args=None,
                      train=False, rng=None, as_string=False, top_modules=3,
                      print_profile=True):
    """Profile a model or bare function (reference ``get_model_profile``,
    ``profiler.py:738``).

    Either ``model`` (with ``.init``/``.apply``) plus ``batch``, or ``fn``
    plus ``args``.  ``train=True`` profiles the full fwd+bwd
    (``value_and_grad``) instead of applying a 3x heuristic.  Returns
    ``(flops, macs, params)`` — formatted strings if ``as_string``.
    """
    if fn is None:
        assert model is not None and batch is not None
        if params is None:
            params = model.init(rng if rng is not None else jax.random.PRNGKey(0))
        if train:
            def fn(p, b):
                return jax.grad(
                    lambda q: model.apply(q, b, rng=None, train=True)
                    .astype(np.float32).sum())(p)
        else:
            def fn(p, b):
                return model.apply(p, b, rng=None, train=False)
        args = (params, batch)
    n_params = params_count(args[0]) if args else 0
    flops, by_scope = count_fn_flops(fn, *args)
    macs = flops // 2
    if print_profile:
        prof = FlopsProfile(flops=flops, macs=macs, params=n_params,
                            by_scope=by_scope)
        prof.print(top_modules=top_modules)
    if as_string:
        return f"{_fmt(flops)}FLOPs", f"{_fmt(macs)}MACs", f"{_fmt(n_params)}params"
    return flops, macs, n_params


class FlopsProfile:
    def __init__(self, flops, macs, params, by_scope=None, wall_ms=None,
                 backend_cost=None):
        self.flops = flops
        self.macs = macs
        self.params = params
        self.by_scope = by_scope or {}
        self.wall_ms = wall_ms
        self.backend_cost = backend_cost or {}

    def achieved_tflops(self):
        if not self.wall_ms:
            return None
        return self.flops / (self.wall_ms / 1e3) / 1e12

    def mfu(self, device=None):
        """Model-FLOPs utilisation against the chip's bf16 peak — the
        SAME peak table bench.py quotes (``profiling/utilization.py``),
        so profiler and bench utilisation cannot drift."""
        if not self.wall_ms:
            return None
        from ..utilization import chip_peak_tflops

        if device is None:
            import jax

            device = jax.devices()[0]
        return self.achieved_tflops() / chip_peak_tflops(device)

    def print(self, top_modules=3, log=None):
        log = log or logger.info
        log(f"flops profile: {_fmt(self.flops)}FLOPs, {_fmt(self.macs)}MACs, "
            f"{_fmt(self.params)}params")
        if self.wall_ms:
            mfu = self.mfu()
            log(f"  wall: {self.wall_ms:.2f} ms -> "
                f"{self.achieved_tflops():.2f} TFLOP/s achieved"
                + (f" (MFU {mfu:.3f})" if mfu is not None else ""))
        if self.backend_cost.get("flops"):
            log(f"  backend cost model: {_fmt(self.backend_cost['flops'])}FLOPs")
        scopes = sorted(self.by_scope.items(), key=lambda kv: -kv[1])
        for name, fl in scopes[:top_modules]:
            log(f"  {100.0 * fl / max(self.flops, 1):5.1f}%  {_fmt(fl)}FLOPs  {name}")


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler``,
    ``profiler.py:11``): profiles the engine's *actual* fused train step —
    forward, backward, optimizer, and collectives as traced — at the
    configured ``profile_step``."""

    def __init__(self, engine):
        self.engine = engine
        self.profile = None

    def profile_train_step(self, batch, wall_ms=None):
        eng = self.engine
        flops, by_scope = count_fn_flops(
            eng._fwd_bwd_fn, eng._forward_params(), eng._shard_batch(batch),
            jax.random.PRNGKey(0), np.float32(1.0), {})
        # optimizer apply cost (elementwise over the flat space); a
        # master-shaped placeholder stands in for the gradient operand
        flat_g_like = eng.state["master"]
        apply_flops, _ = count_fn_flops(
            eng._apply_fn, eng.state["master"], eng.state["opt"],
            eng.state["scale"], eng.state["skipped"], flat_g_like,
            eng._device_hyperparams(), eng._segment_ids)
        total = flops * eng.gradient_accumulation_steps() + apply_flops
        self.profile = FlopsProfile(
            flops=total, macs=total // 2,
            params=params_count(eng._param_template), by_scope=by_scope,
            wall_ms=wall_ms)
        return self.profile

    def print_model_profile(self, batch=None, top_modules=3):
        if self.profile is None:
            assert batch is not None, "first call needs a sample batch"
            self.profile_train_step(batch)
        self.profile.print(top_modules=top_modules)

