"""Flops-profiler config (reference ``deepspeed/profiling/config.py``)."""

FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True


class DeepSpeedFlopsProfilerConfig:
    def __init__(self, param_dict):
        d = param_dict.get(FLOPS_PROFILER, {})
        self.enabled = d.get(FLOPS_PROFILER_ENABLED, FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = d.get(FLOPS_PROFILER_PROFILE_STEP, FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = d.get(FLOPS_PROFILER_MODULE_DEPTH, FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = d.get(FLOPS_PROFILER_TOP_MODULES, FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = d.get(FLOPS_PROFILER_DETAILED, FLOPS_PROFILER_DETAILED_DEFAULT)

    def repr(self):
        return dict(enabled=self.enabled, profile_step=self.profile_step,
                    module_depth=self.module_depth, top_modules=self.top_modules,
                    detailed=self.detailed)
