"""Profiling configs: the reference-parity ``flops_profiler`` block and
the ``profiling`` block (memory ledger + watermarks, new)."""

FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True


class DeepSpeedFlopsProfilerConfig:
    def __init__(self, param_dict):
        d = param_dict.get(FLOPS_PROFILER, {})
        self.enabled = d.get(FLOPS_PROFILER_ENABLED, FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = d.get(FLOPS_PROFILER_PROFILE_STEP, FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = d.get(FLOPS_PROFILER_MODULE_DEPTH, FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = d.get(FLOPS_PROFILER_TOP_MODULES, FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = d.get(FLOPS_PROFILER_DETAILED, FLOPS_PROFILER_DETAILED_DEFAULT)

    def repr(self):
        return dict(enabled=self.enabled, profile_step=self.profile_step,
                    module_depth=self.module_depth, top_modules=self.top_modules,
                    detailed=self.detailed)


def _tristate(value, name):
    """"auto" | true | false (same convention as compilation.cache)."""
    if value in (True, False) or value == "auto":
        return value
    raise ValueError(f"profiling.{name} must be true, false or \"auto\", "
                     f"got {value!r}")


class DeepSpeedProfilingConfig:
    """Typed view of the ``profiling`` block (memory observability)."""

    def __init__(self, param_dict):
        from ..runtime import constants as C
        from ..runtime.config_utils import get_scalar_param

        prof = param_dict.get(C.PROFILING, {}) or {}
        self.memory_ledger = _tristate(get_scalar_param(
            prof, C.PROFILING_MEMORY_LEDGER,
            C.PROFILING_MEMORY_LEDGER_DEFAULT), C.PROFILING_MEMORY_LEDGER)
        self.memory_watermarks = _tristate(get_scalar_param(
            prof, C.PROFILING_MEMORY_WATERMARKS,
            C.PROFILING_MEMORY_WATERMARKS_DEFAULT),
            C.PROFILING_MEMORY_WATERMARKS)
        self.comm_ledger = _tristate(get_scalar_param(
            prof, C.PROFILING_COMM_LEDGER,
            C.PROFILING_COMM_LEDGER_DEFAULT), C.PROFILING_COMM_LEDGER)
        self.program_dump = _tristate(get_scalar_param(
            prof, C.PROFILING_PROGRAM_DUMP,
            C.PROFILING_PROGRAM_DUMP_DEFAULT), C.PROFILING_PROGRAM_DUMP)

    def comm_ledger_enabled(self, telemetry_enabled):
        if self.comm_ledger == "auto":
            return bool(telemetry_enabled)
        return bool(self.comm_ledger)

    def memory_ledger_enabled(self, telemetry_enabled):
        if self.memory_ledger == "auto":
            return bool(telemetry_enabled)
        return bool(self.memory_ledger)

    def program_dump_enabled(self, comm_ledger_enabled):
        """Whether per-program verification artifacts (HLO + sidecar)
        should land under the run dir.  "auto" follows the comm ledger:
        the dump consumes exactly what that hook already captures."""
        if self.program_dump == "auto":
            return bool(comm_ledger_enabled)
        return bool(self.program_dump)

    def memory_watermarks_enabled(self, telemetry_enabled):
        # watermark output is gauges/events — without telemetry there is
        # no sink, so "true" still requires telemetry to matter
        if self.memory_watermarks == "auto":
            return bool(telemetry_enabled)
        return bool(self.memory_watermarks) and bool(telemetry_enabled)

    def __repr__(self):
        return (f"DeepSpeedProfilingConfig(memory_ledger="
                f"{self.memory_ledger!r}, memory_watermarks="
                f"{self.memory_watermarks!r}, comm_ledger="
                f"{self.comm_ledger!r}, program_dump="
                f"{self.program_dump!r})")
