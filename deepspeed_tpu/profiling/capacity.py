"""AOT capacity planner: "will this config fit?" without a trial run.

``python -m deepspeed_tpu.profiling.capacity --config ds_config.json
--model gpt2-xl`` builds the engine in *plan mode* (``aot_plan=True``:
step programs are built and jitted, module params never materialize on
device), lowers + compiles the fused train step WITHOUT executing it,
and reads the executable's ``memory_analysis()`` — the compiler's own
statement of argument/output/temp/alias bytes.  Warm under the PR 5
persistent compile cache this is a seconds-long query; each capacity
ladder rung used to cost a full trial run to learn the same answer by
OOM-ing (ROADMAP item 1).

Verdict: ``predicted peak HBM = arguments + outputs − aliased (donated)
+ temporaries + generated code`` per device, compared against the
device's ``memory_stats()['bytes_limit']`` (or ``--capacity-gb``)
scaled by ``--headroom``.  ``--bisect-layers LO HI`` bisects the layer
count to estimate the largest fitting model of the family.

Exit codes: 0 fit (and the compiled programs carry no error-severity
DSP6xx findings), 1 no-fit OR error-severity DSP6xx program-verifier
findings (a plan whose step program drops its donation aliases or sums
parameters over the wrong mesh axis is a failed plan even when the
bytes fit; heuristic DSP warnings print but do not gate — the planner
has no ratchet), 2 usage error, 3 unknown (the backend lacks
``memory_analysis`` or no device capacity is known — fail-soft by
design, the planner must degrade to "unknown", never crash).
"""

import argparse
import gc
import json
import sys
import time

# model presets: name -> GPT2Config kwargs (the bench/ladder shapes)
GPT2_PRESETS = {
    "gpt2-medium": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": dict(hidden_size=1280, num_layers=36, num_heads=20),
    "gpt2-xl": dict(hidden_size=1600, num_layers=48, num_heads=25),
    "gpt2-2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32),
    "gpt2-4b": dict(hidden_size=3072, num_layers=36, num_heads=32),
    "gpt2-6b": dict(hidden_size=4096, num_layers=30, num_heads=32),
}

DEFAULT_HEADROOM = 0.92


def gpt2_param_count(hidden_size, num_layers, vocab_size=50257,
                     max_position_embeddings=1024):
    """Analytic GPT-2 parameter count (tied LM head)."""
    h, L = hidden_size, num_layers
    per_layer = 12 * h * h + 13 * h  # qkv/proj/mlp + ln/biases
    return vocab_size * h + max_position_embeddings * h \
        + L * per_layer + 2 * h


def _build_model(args, num_layers=None):
    from ..models import GPT2Config, GPT2LMHeadTPU

    explicit = (args.hidden, args.layers, args.heads)
    if all(explicit):
        # explicit dims always win over the --model preset default
        kw = dict(hidden_size=args.hidden, num_layers=args.layers,
                  num_heads=args.heads)
    elif any(explicit):
        # a PARTIAL spec must not silently plan the preset default —
        # the verdict would be about a different model than asked
        raise ValueError(
            "--hidden/--layers/--heads must all be given together "
            f"(got hidden={args.hidden} layers={args.layers} "
            f"heads={args.heads})")
    elif args.model in GPT2_PRESETS:
        kw = dict(GPT2_PRESETS[args.model])
    else:
        raise ValueError(
            f"--model must be one of {sorted(GPT2_PRESETS)} or "
            "--hidden/--layers/--heads must all be given")
    if num_layers is not None:
        kw["num_layers"] = int(num_layers)
    cfg = GPT2Config(max_position_embeddings=args.seq, embd_dropout=0.0,
                     attn_dropout=0.0, resid_dropout=0.0, remat=True,
                     loss_chunk=(256 if args.seq % 256 == 0 else None), **kw)
    return GPT2LMHeadTPU(cfg), kw


def device_capacity_bytes(capacity_gb=None):
    """Per-device HBM capacity: explicit override, else
    ``memory_stats()['bytes_limit']`` of local device 0 (None when the
    backend reports nothing — CPU)."""
    if capacity_gb:
        return int(capacity_gb * (1 << 30))
    from .memory import device_memory_summary

    try:
        import jax

        summary = device_memory_summary(devices=jax.local_devices()[:1])
    except Exception:  # dslint: disable=DSE502 -- no backend: capacity unknown
        return None
    return summary["bytes_limit"] if summary["reporting"] else None


def plan(config, model, sample_batch, mesh=None, capacity_bytes=None,
         headroom=DEFAULT_HEADROOM):
    """Compile-only fit analysis for one (config, model) pair.

    Returns a dict: per-space byte breakdown, predicted peak, capacity,
    and ``fit`` (True/False/None-unknown).  Fail-soft: a backend without
    ``memory_analysis`` yields ``predicted_peak_hbm_bytes=None`` and
    ``fit=None``."""
    import deepspeed_tpu as deepspeed

    from .memory import predicted_host_bytes, predicted_peak_bytes

    t0 = time.perf_counter()
    if mesh is None:
        # single-chip planning by default: "will this fit ONE device" is
        # the capacity-ladder question (pass a mesh for multi-chip plans)
        import jax

        from ..parallel import make_mesh

        mesh = make_mesh({"data": 1}, devices=[jax.devices()[0]])
    engine, *_ = deepspeed.initialize(model=model, config=config,
                                      mesh=mesh, aot_plan=True)
    try:
        _, entry = engine.aot_compile_train_step(sample_batch)
        # program-level semantic verification (DSP6xx) at plan time:
        # the compiled step is already in the ledger, so a donation or
        # collective-semantics bug fails the PLAN, not the 2-AM run
        verify = engine.verify_programs()
        out = {
            "analysis_available": entry is not None,
            "dsp_violations": (verify["violations"]
                               if verify is not None else None),
            "dsp_errors": (verify["errors"]
                           if verify is not None else None),
            "dsp_downgraded": (verify["downgraded"]
                               if verify is not None else None),
            "dsp_findings": ([d.format() for d in verify["diagnostics"]
                              if not d.suppressed]
                             if verify is not None else []),
            # static overlap verdict (profiling/overlap, DSO7xx): the
            # plan states not just whether the step fits but how much
            # of its predicted wire is exposed as latency
            "exposed_wire_seconds": (
                verify["overlap"]["exposed_wire_seconds"]
                if verify is not None and verify.get("overlap")
                else None),
            "overlap_fraction": (
                verify["overlap"]["overlap_fraction"]
                if verify is not None and verify.get("overlap")
                else None),
            # static residency verdict (profiling/sharding, DSS8xx):
            # the per-device parameter bytes the compiled step's entry
            # layout actually materializes, with the shard divisor —
            # ROADMAP item 2's planner-verified ÷dp receipt
            "param_bytes_per_device": (
                (verify["sharding"].get("train_step") or {}).get(
                    "param_bytes_per_device")
                if verify is not None and verify.get("sharding")
                else None),
            "param_bytes_global": (
                (verify["sharding"].get("train_step") or {}).get(
                    "param_bytes_global")
                if verify is not None and verify.get("sharding")
                else None),
            "param_shard_divisor": (
                (verify["sharding"].get("train_step") or {}).get(
                    "param_shard_divisor")
                if verify is not None and verify.get("sharding")
                else None),
            "predicted_peak_hbm_bytes": predicted_peak_bytes(entry),
            "predicted_temp_bytes": (entry or {}).get("temp_size_in_bytes"),
            "argument_bytes": (entry or {}).get("argument_size_in_bytes"),
            "output_bytes": (entry or {}).get("output_size_in_bytes"),
            "alias_bytes": (entry or {}).get("alias_size_in_bytes"),
            "generated_code_bytes": (entry or {}).get(
                "generated_code_size_in_bytes"),
            "predicted_host_bytes": predicted_host_bytes(entry),
            "host_buffer_bytes":
                engine.memory_ledger.host_buffers.total_bytes(),
            "host_buffer_count":
                engine.memory_ledger.host_buffers.total_count(),
            "host_state_wire_bytes_per_step":
                engine.host_state_bytes_per_step(),
            "capacity_bytes": capacity_bytes,
            "headroom": headroom,
            "plan_seconds": round(time.perf_counter() - t0, 3),
        }
        peak = out["predicted_peak_hbm_bytes"]
        if peak is None or capacity_bytes is None:
            out["fit"] = None
        else:
            out["fit"] = peak <= capacity_bytes * headroom
        return out
    finally:
        engine.close()
        del engine
        gc.collect()


def bisect_max_layers(args, config, mesh, capacity_bytes, lo, hi,
                      log=print):
    """Largest layer count in [lo, hi] whose plan fits (None when even
    ``lo`` does not fit or fit is unknowable)."""
    batch = _sample_batch(args)
    best = None
    while lo <= hi:
        mid = (lo + hi) // 2
        model, kw = _build_model(args, num_layers=mid)
        result = plan(config, model, batch, mesh=mesh,
                      capacity_bytes=capacity_bytes,
                      headroom=args.headroom)
        del model
        gc.collect()
        if result["fit"] is None:
            log(f"# bisect: fit unknowable at layers={mid}; stopping")
            return None, None
        params = gpt2_param_count(kw["hidden_size"], mid,
                                  max_position_embeddings=args.seq)
        log(f"# bisect: layers={mid} params={params / 1e9:.2f}B "
            f"peak={result['predicted_peak_hbm_bytes']} "
            f"fit={result['fit']}")
        if result["fit"]:
            best = (mid, params)
            lo = mid + 1
        else:
            hi = mid - 1
    return best if best else (None, None)


def _sample_batch(args):
    import numpy as np

    return {"input_ids": np.zeros((args.batch, args.seq), np.int32)}


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.profiling.capacity",
        description="AOT capacity planner: compile the train step, "
                    "predict peak HBM, emit a fit/no-fit verdict — no "
                    "trial run")
    parser.add_argument("--config", required=True,
                        help="DeepSpeed config JSON (the training config "
                             "to plan for)")
    parser.add_argument("--model", default="gpt2-xl",
                        help=f"model preset ({', '.join(sorted(GPT2_PRESETS))})"
                             " or use --hidden/--layers/--heads")
    parser.add_argument("--hidden", type=int, default=0)
    parser.add_argument("--layers", type=int, default=0)
    parser.add_argument("--heads", type=int, default=0)
    parser.add_argument("--batch", type=int, default=0,
                        help="micro-batch size (default: derived from the "
                             "config's train_batch_size / "
                             "gradient_accumulation_steps at dp=1)")
    parser.add_argument("--seq", type=int, default=1024)
    parser.add_argument("--zero-stage", type=int, default=-1,
                        dest="zero_stage",
                        help="override the config's zero_optimization."
                             "stage — plan the SAME model/geometry under "
                             "a different stage (the stage-2 vs stage-3 "
                             "capacity question)")
    parser.add_argument("--dp", type=int, default=1,
                        help="data-parallel width to plan at (mesh over "
                             "the first N local devices; under stage 3 "
                             "the persistent parameter state shards ÷N)")
    parser.add_argument("--capacity-gb", type=float, default=0.0,
                        help="per-device HBM capacity override (GiB); "
                             "default: memory_stats()['bytes_limit']")
    parser.add_argument("--headroom", type=float, default=DEFAULT_HEADROOM,
                        help="usable fraction of capacity (allocator "
                             "fragmentation margin)")
    parser.add_argument("--bisect-layers", type=int, nargs=2,
                        metavar=("LO", "HI"),
                        help="also bisect num_layers in [LO, HI] for the "
                             "max fitting model size")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit one JSON line instead of the report")
    args = parser.parse_args(argv)

    try:
        with open(args.config, encoding="utf-8") as f:
            config = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: cannot read --config {args.config}: {e}",
              file=sys.stderr)
        return 2

    if args.zero_stage >= 0:
        zero = dict(config.get("zero_optimization") or {})
        zero["stage"] = args.zero_stage
        config["zero_optimization"] = zero

    if not args.batch:
        tbs = int(config.get("train_batch_size", 4) or 4)
        acc = int(config.get("gradient_accumulation_steps", 1) or 1)
        args.batch = max(1, tbs // acc)

    mesh = None
    if args.dp > 1:
        import jax

        from ..parallel import make_mesh

        avail = len(jax.devices())
        if args.dp > avail:
            print(f"error: --dp {args.dp} exceeds the {avail} local "
                  "device(s)", file=sys.stderr)
            return 2
        mesh = make_mesh({"data": args.dp},
                         devices=jax.devices()[:args.dp])

    capacity = device_capacity_bytes(args.capacity_gb or None)
    try:
        model, kw = _build_model(args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        result = plan(config, model, _sample_batch(args), mesh=mesh,
                      capacity_bytes=capacity, headroom=args.headroom)
    except Exception as e:
        # the exit-code contract reserves 1 for NO-FIT: a crashed plan
        # (bad config, compile failure) must not read as "does not fit"
        print(f"error: capacity plan failed: {e!r:.500}", file=sys.stderr)
        return 2
    del model
    gc.collect()
    result["model"] = (f"gpt2(h{args.hidden},L{args.layers})"
                       if args.hidden and args.layers and args.heads
                       else args.model)
    result["params_b"] = round(gpt2_param_count(
        kw["hidden_size"], kw["num_layers"],
        max_position_embeddings=args.seq) / 1e9, 3)
    result["batch"], result["seq"] = args.batch, args.seq
    result["zero_stage"] = int((config.get("zero_optimization") or {})
                               .get("stage", 0) or 0)
    result["dp"] = args.dp

    if args.bisect_layers:
        try:
            layers, params = bisect_max_layers(
                args, config, mesh, capacity, *args.bisect_layers,
                log=(lambda *a: None) if args.as_json else print)
        except Exception as e:
            print(f"error: bisect failed: {e!r:.500}", file=sys.stderr)
            layers = params = None
        result["max_fitting_layers"] = layers
        result["max_fitting_params_b"] = (round(params / 1e9, 3)
                                          if params else None)

    if args.as_json:
        print(json.dumps(result))
    else:
        _print_report(result)
    if result.get("dsp_errors"):
        # a step program that fails semantic verification with an
        # ERROR-severity finding (donation aliases dropped, parameter
        # sum on the wrong mesh axis) is a failed plan even when the
        # bytes fit — DSP601's own rationale is that dropped aliases
        # make the capacity math wrong.  Heuristic WARNINGS
        # (psum-for-pmean suspects, ledger drift) print in the report
        # but do not gate: the planner has no --baseline ratchet to
        # absolve an intentional psum
        return 1
    if result["fit"] is True:
        return 0
    if result["fit"] is False:
        return 1
    return 3


def _fmt_bytes(n):
    if n is None:
        return "unknown"
    return f"{n / (1 << 30):.2f} GiB ({n})"


def _print_report(r):
    print(f"capacity plan: {r.get('model')} ({r.get('params_b')}B params) "
          f"batch={r.get('batch')} seq={r.get('seq')} "
          f"zero-stage={r.get('zero_stage', '?')} dp={r.get('dp', 1)}")
    print(f"  predicted peak HBM ... {_fmt_bytes(r['predicted_peak_hbm_bytes'])}")
    print(f"    arguments .......... {_fmt_bytes(r['argument_bytes'])}")
    print(f"    outputs ............ {_fmt_bytes(r['output_bytes'])}")
    print(f"    aliased (donated) .. -{_fmt_bytes(r['alias_bytes'])}")
    if r["alias_bytes"] == 0 and r["analysis_available"]:
        # measured: executables deserialized from the persistent compile
        # cache can report alias_size_in_bytes=0 even though the program
        # donates its state buffers — the peak then OVERCOUNTS donated
        # arguments (conservative: never claims fit falsely)
        print("    (no aliasing reported — cache-deserialized "
              "executables may omit it; peak is conservative)")
    print(f"    temporaries ........ {_fmt_bytes(r['predicted_temp_bytes'])}")
    print(f"    generated code ..... {_fmt_bytes(r['generated_code_bytes'])}")
    print(f"  predicted host bytes . {_fmt_bytes(r['predicted_host_bytes'])}")
    print(f"  pinned host buffers .. {r['host_buffer_count']} buffer(s), "
          f"{_fmt_bytes(r['host_buffer_bytes'])}")
    if r.get("host_state_wire_bytes_per_step"):
        print(f"  state wire bytes/step  "
              f"{_fmt_bytes(r['host_state_wire_bytes_per_step'])}")
    if r.get("dsp_violations") is not None:
        verdict = ("clean" if r["dsp_violations"] == 0
                   else f"{r['dsp_violations']} VIOLATION(S)")
        # DSP602 covers several downgrade causes (warm-cache alias=0,
        # absent byte data, partial-alias drop) — the finding lines
        # below carry the specific diagnosis
        extra = (f", {r['dsp_downgraded']} downgraded verdict(s) "
                 "(DSP602 — see findings)"
                 if r.get("dsp_downgraded") else "")
        print(f"  program verify ....... {verdict}{extra}")
        for line in r.get("dsp_findings") or []:
            print(f"    {line}")
    if r.get("exposed_wire_seconds") is not None:
        print(f"  exposed wire ......... "
              f"{r['exposed_wire_seconds'] * 1e3:.3f} ms/step "
              f"(overlap fraction {r['overlap_fraction']:.2f})")
    if r.get("param_bytes_per_device") is not None:
        div = r.get("param_shard_divisor") or 1
        print(f"  params per device .... "
              f"{_fmt_bytes(r['param_bytes_per_device'])} "
              f"(global {_fmt_bytes(r.get('param_bytes_global'))} "
              f"÷{div} shard)")
    print(f"  device capacity ...... {_fmt_bytes(r['capacity_bytes'])} "
          f"(headroom {r['headroom']:.2f})")
    if r["fit"] is None:
        why = ("backend lacks memory_analysis"
               if not r["analysis_available"]
               else "device capacity unknown; pass --capacity-gb")
        print(f"  verdict .............. UNKNOWN ({why})")
    else:
        print(f"  verdict .............. {'FIT' if r['fit'] else 'NO FIT'}")
    if "max_fitting_layers" in r:
        print(f"  max fitting layers ... {r['max_fitting_layers']} "
              f"(~{r['max_fitting_params_b']}B params)")
    print(f"  planned in ........... {r['plan_seconds']} s "
          f"(warm compile cache makes reruns ~free)")


if __name__ == "__main__":
    sys.exit(main())
