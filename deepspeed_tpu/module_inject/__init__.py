"""Kernel/module injection (reference ``deepspeed/module_inject/``)."""

from .replace_module import (inject_bert_layer, replace_module,
                             replace_transformer_layer, revert_bert_layer)

__all__ = ["inject_bert_layer", "replace_module",
           "replace_transformer_layer", "revert_bert_layer"]
