"""Kernel/module injection (reference ``deepspeed/module_inject/``)."""

from .replace_module import (cast_weights, ingest_gpt2_model,
                             inject_bert_layer, inject_gpt2_layer,
                             replace_gpt2_transformer_layer, replace_module,
                             replace_transformer_layer, revert_bert_layer,
                             revert_gpt2_layer)

__all__ = ["cast_weights", "ingest_gpt2_model", "inject_bert_layer",
           "inject_gpt2_layer", "replace_gpt2_transformer_layer",
           "replace_module", "replace_transformer_layer",
           "revert_bert_layer", "revert_gpt2_layer"]
