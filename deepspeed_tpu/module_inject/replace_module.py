"""Module injection: swap HuggingFace (Flax) BERT layers for the
framework's fused transformer layer, by pure weight surgery.

TPU-native analog of the reference ``deepspeed/module_inject/
replace_module.py:6-193``: the reference walks an ``nn.Module`` tree and
replaces ``BertLayer`` instances with ``DeepSpeedTransformerLayer``,
concatenating q/k/v weights into the fused qkv parameter; the revert path
restores the original module for checkpoint export.  Parameters in JAX are
plain pytrees, so injection is a pytree→pytree transform:

- :func:`inject_bert_layer` / :func:`revert_bert_layer` — one encoder
  layer's HF Flax params ↔ ``TransformerLayer`` params (qkv concat, the
  reference's ``replace_transformer_layer`` weight copy).
- :func:`replace_transformer_layer` — full HF ``FlaxBertModel`` encoder
  params → ``{layer_i: our params}`` (+ revert).
- :func:`replace_module` — generic walker applying a policy at every
  matching subtree (reference ``replace_module`` ``:161-193``).

Numerics: our layer is post-LayerNorm with tanh-GELU, matching HF's
``hidden_act='gelu_new'``; exact-GELU checkpoints differ only in the MLP
activation (<1e-3 in bf16).
"""

import jax.numpy as jnp


def inject_bert_layer(hf_layer):
    """HF FlaxBertLayer params → ``TransformerLayer`` params (qkv fused)."""
    att = hf_layer["attention"]
    self_att = att["self"]
    qkv_kernel = jnp.concatenate(
        [self_att["query"]["kernel"], self_att["key"]["kernel"],
         self_att["value"]["kernel"]], axis=1)
    qkv_bias = jnp.concatenate(
        [self_att["query"]["bias"], self_att["key"]["bias"],
         self_att["value"]["bias"]], axis=0)
    return {
        "qkv": {"kernel": qkv_kernel, "bias": qkv_bias},
        "attn_out": {"kernel": att["output"]["dense"]["kernel"],
                     "bias": att["output"]["dense"]["bias"]},
        "fc1": {"kernel": hf_layer["intermediate"]["dense"]["kernel"],
                "bias": hf_layer["intermediate"]["dense"]["bias"]},
        "fc2": {"kernel": hf_layer["output"]["dense"]["kernel"],
                "bias": hf_layer["output"]["dense"]["bias"]},
        "ln_attn": {"scale": att["output"]["LayerNorm"]["scale"],
                    "bias": att["output"]["LayerNorm"]["bias"]},
        "ln_mlp": {"scale": hf_layer["output"]["LayerNorm"]["scale"],
                   "bias": hf_layer["output"]["LayerNorm"]["bias"]},
    }


def revert_bert_layer(ours, hidden_size):
    """``TransformerLayer`` params → HF FlaxBertLayer params (checkpoint
    export; reference revert path)."""
    h = hidden_size
    k = ours["qkv"]["kernel"]
    b = ours["qkv"]["bias"]
    return {
        "attention": {
            "self": {
                "query": {"kernel": k[:, :h], "bias": b[:h]},
                "key": {"kernel": k[:, h:2 * h], "bias": b[h:2 * h]},
                "value": {"kernel": k[:, 2 * h:], "bias": b[2 * h:]},
            },
            "output": {
                "dense": {"kernel": ours["attn_out"]["kernel"],
                          "bias": ours["attn_out"]["bias"]},
                "LayerNorm": {"scale": ours["ln_attn"]["scale"],
                              "bias": ours["ln_attn"]["bias"]},
            },
        },
        "intermediate": {"dense": {"kernel": ours["fc1"]["kernel"],
                                   "bias": ours["fc1"]["bias"]}},
        "output": {
            "dense": {"kernel": ours["fc2"]["kernel"],
                      "bias": ours["fc2"]["bias"]},
            "LayerNorm": {"scale": ours["ln_mlp"]["scale"],
                          "bias": ours["ln_mlp"]["bias"]},
        },
    }


def replace_transformer_layer(hf_encoder_params, revert=False,
                              hidden_size=None):
    """Convert every layer of an HF Flax BERT encoder param tree
    (``{'layer': {'0': ..., '1': ...}}`` or ``{'0': ...}``) to fused-layer
    params keyed ``layer_i`` — or back with ``revert=True`` (reference
    ``replace_transformer_layer``, ``module_inject/replace_module.py:6``).
    """
    layers = hf_encoder_params.get("layer", hf_encoder_params)
    out = {}
    for key, sub in layers.items():
        idx = int(str(key).split("_")[-1]) if not str(key).isdigit() else int(key)
        if revert:
            assert hidden_size is not None, "revert needs hidden_size"
            out[str(idx)] = revert_bert_layer(sub, hidden_size)
        else:
            out[f"layer_{idx}"] = inject_bert_layer(sub)
    return out


def inject_gpt2_layer(hf_block):
    """HF FlaxGPT2Block params → ``TransformerLayer`` params.

    GPT-2's ``c_attn`` already stores the fused ``[h, 3h]`` qkv kernel
    (HF keeps the original TF Conv1D layout, which in Flax lands as a
    plain ``[in, out]`` dense kernel), so unlike the BERT policy there
    is no concat — the surgery is a pure re-keying: ``ln_1``/``ln_2``
    become the pre-LN ``ln_attn``/``ln_mlp`` our layer's
    ``pre_layer_norm`` path reads."""
    att = hf_block["attn"]
    mlp = hf_block["mlp"]
    return {
        "qkv": {"kernel": att["c_attn"]["kernel"],
                "bias": att["c_attn"]["bias"]},
        "attn_out": {"kernel": att["c_proj"]["kernel"],
                     "bias": att["c_proj"]["bias"]},
        "fc1": {"kernel": mlp["c_fc"]["kernel"],
                "bias": mlp["c_fc"]["bias"]},
        "fc2": {"kernel": mlp["c_proj"]["kernel"],
                "bias": mlp["c_proj"]["bias"]},
        "ln_attn": {"scale": hf_block["ln_1"]["scale"],
                    "bias": hf_block["ln_1"]["bias"]},
        "ln_mlp": {"scale": hf_block["ln_2"]["scale"],
                   "bias": hf_block["ln_2"]["bias"]},
    }


def revert_gpt2_layer(ours):
    """``TransformerLayer`` params → HF FlaxGPT2Block params (checkpoint
    export).  Exact inverse of :func:`inject_gpt2_layer` — the fused qkv
    kernel passes through whole, so no ``hidden_size`` is needed."""
    return {
        "ln_1": {"scale": ours["ln_attn"]["scale"],
                 "bias": ours["ln_attn"]["bias"]},
        "attn": {
            "c_attn": {"kernel": ours["qkv"]["kernel"],
                       "bias": ours["qkv"]["bias"]},
            "c_proj": {"kernel": ours["attn_out"]["kernel"],
                       "bias": ours["attn_out"]["bias"]},
        },
        "ln_2": {"scale": ours["ln_mlp"]["scale"],
                 "bias": ours["ln_mlp"]["bias"]},
        "mlp": {
            "c_fc": {"kernel": ours["fc1"]["kernel"],
                     "bias": ours["fc1"]["bias"]},
            "c_proj": {"kernel": ours["fc2"]["kernel"],
                       "bias": ours["fc2"]["bias"]},
        },
    }


def replace_gpt2_transformer_layer(hf_blocks, revert=False):
    """Convert every block of an HF Flax GPT-2 transformer
    (``{'h': {'0': ..., '1': ...}}`` or ``{'0': ...}``) to fused-layer
    params keyed ``layer_i`` — or back with ``revert=True`` — mirroring
    the BERT pair above."""
    blocks = hf_blocks.get("h", hf_blocks)
    out = {}
    for key, sub in blocks.items():
        idx = int(str(key).split("_")[-1]) if not str(key).isdigit() \
            else int(key)
        if revert:
            out[str(idx)] = revert_gpt2_layer(sub)
        else:
            out[f"layer_{idx}"] = inject_gpt2_layer(sub)
    return out


def ingest_gpt2_model(hf_params):
    """Full HF ``FlaxGPT2LMHeadModel`` param tree →
    :class:`~deepspeed_tpu.models.gpt2.GPT2LMHeadTPU` params: embeddings
    remapped (``wte.embedding`` → ``wte``), every block through the
    injection policy, final layernorm carried over.  Accepts either the
    full tree (``{'transformer': {...}}``) or the transformer subtree."""
    t = hf_params.get("transformer", hf_params)
    return {
        "wte": t["wte"]["embedding"],
        "wpe": t["wpe"]["embedding"],
        "blocks": replace_gpt2_transformer_layer(t),
        "ln_f": {"scale": t["ln_f"]["scale"], "bias": t["ln_f"]["bias"]},
    }


def cast_weights(params, dtype):
    """Cast every floating-point leaf of a param tree to ``dtype``
    (serving-time bf16 ingestion; integer leaves — e.g. token tables —
    pass through untouched)."""
    import jax

    def cast(leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            return arr.astype(dtype)
        return arr

    return jax.tree_util.tree_map(cast, params)


def replace_module(params, policy, match):
    """Generic walker (reference ``replace_module``, ``:161-193``): apply
    ``policy(subtree)`` to every subtree for which ``match(path, subtree)``
    is True; other nodes copied unchanged.  ``path`` is a '/'-joined key
    string."""

    def walk(node, path):
        if isinstance(node, dict):
            if match(path, node):
                return policy(node)
            return {k: walk(v, f"{path}/{k}" if path else str(k))
                    for k, v in node.items()}
        return node

    return walk(params, "")
