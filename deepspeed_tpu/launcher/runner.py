"""Multi-node launcher front-end.

TPU-native analog of the reference ``deepspeed/launcher/runner.py:254-330``:
reads a hostfile, applies ``--include``/``--exclude`` node/slot filters,
encodes the resource map, and either execs the per-node spawner directly
(single node) or fans out over pdsh/ssh (multi node).  The per-process env
contract it establishes (``DS_COORDINATOR``/``DS_NUM_PROCESSES``/
``DS_PROCESS_ID``) is what ``utils/distributed.init_distributed`` feeds to
``jax.distributed.initialize`` — coordinator-based rendezvous instead of
the reference's MASTER_ADDR process groups.

Usage::

    deepspeed [--hostfile H] [--include w1@w2:0,1] [--num_nodes N]
              [--num_procs P] your_script.py --your-args
"""

import argparse
import base64
import json
import os
import re
import shlex
import subprocess
import sys

from ..utils.logging import logger
from .constants import (DEFAULT_HOSTFILE, DEFAULT_MASTER_PORT,
                        DEFAULT_PROCS_PER_NODE, ENV_COORDINATOR,
                        ENV_NUM_PROCESSES, MVAPICH_LAUNCHER,
                        OPENMPI_LAUNCHER, PDSH_LAUNCHER, SSH_LAUNCHER)

#: env-var name prefixes forwarded to every worker process (reference
#: ``runner.py:27`` exports NCCL/PYTHON/MV2/UCX; the TPU runtime's knobs
#: live under JAX_*/XLA_*/LIBTPU_*/TPU_* instead, and the framework's own
#: DS_* feature toggles must reach workers too)
EXPORT_ENVS = ("JAX", "XLA", "LIBTPU", "TPU", "PYTHON", "MV2", "UCX", "DS_")
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = (os.path.expanduser("~"), ".")

#: per-process rendezvous vars the spawners own — forwarding a stale copy
#: from the launcher's shell would make every rank claim the same id (the
#: MPI path has no per-child override, unlike launch.py)
_NO_FORWARD = frozenset(("DS_COORDINATOR", "DS_NUM_PROCESSES",
                         "DS_PROCESS_ID", "DS_LOCAL_RANK"))

_ENV_KEY_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def collect_exports(environ=None, paths=DEEPSPEED_ENVIRONMENT_PATHS):
    """Env vars that must travel to worker processes: every var whose name
    starts with an ``EXPORT_ENVS`` prefix, then ``KEY=VALUE`` lines from
    ``.deepspeed_env`` files (reference ``runner.py:341-356``; file entries
    override inherited env, later files override earlier ones)."""
    environ = os.environ if environ is None else environ
    exports = {}
    for k, v in environ.items():
        if not any(k.startswith(p) for p in EXPORT_ENVS) or k in _NO_FORWARD:
            continue
        # names with shell-illegal chars (legal in the process environment)
        # would break the remote `export` silently — skip them loudly
        if not _ENV_KEY_RE.match(k):
            logger.warning(f"not forwarding env var {k!r}: name is not a "
                           "shell identifier")
            continue
        exports[k] = v
    for d in paths:
        path = os.path.join(d, DEEPSPEED_ENVIRONMENT_NAME)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                key, sep, val = line.partition("=")
                key = key.strip()
                # fail at parse time, not as a shell error on remote hosts
                if not sep or not _ENV_KEY_RE.match(key):
                    raise ValueError(
                        f"malformed line in {path}: {line!r} "
                        "(expected SHELL_IDENTIFIER=value)")
                if key not in _NO_FORWARD:
                    exports[key] = val.strip()
    return exports


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU multi-node launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DEFAULT_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="nodes/slots to include, e.g. "
                             "'worker-0@worker-1:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="nodes/slots to exclude, e.g. 'worker-1:0'")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="cap on node count (first N of the hostfile)")
    parser.add_argument("--num_procs", type=int, default=-1,
                        help="processes per node (default: hostfile slots, "
                             f"or {DEFAULT_PROCS_PER_NODE})")
    parser.add_argument("--master_addr", type=str, default="",
                        help="coordinator address (default: first node)")
    parser.add_argument("--master_port", type=int, default=DEFAULT_MASTER_PORT)
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        choices=[PDSH_LAUNCHER, SSH_LAUNCHER,
                                 OPENMPI_LAUNCHER, MVAPICH_LAUNCHER])
    parser.add_argument("--force_multi", action="store_true",
                        help="treat as multi-node even for one host")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path):
    """Parse 'hostname slots=N' lines (reference ``runner.py:115-143``).
    Returns an ordered {hostname: slots} dict; {} when the file is absent
    (single-node fallback)."""
    if not os.path.isfile(path):
        return {}
    pool = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                host, slots = line.split()
                key, n = slots.split("=")
                assert key == "slots"
                n = int(n)
            except Exception as e:
                raise ValueError(f"malformed hostfile line: {line!r}") from e
            if host in pool:
                raise ValueError(f"duplicate host {host!r} in hostfile")
            pool[host] = n
    return pool


def _parse_filter(spec):
    """'w0@w1:0,2' -> {'w0': None, 'w1': [0, 2]} (None = every slot)."""
    out = {}
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host.strip()] = sorted(int(s) for s in slots.split(","))
        else:
            out[part] = None
    return out


def filter_resources(pool, include="", exclude=""):
    """Apply include/exclude filters (reference ``runner.py:146-245``).
    Returns ordered {host: [slot ids]}."""
    assert not (include and exclude), "--include and --exclude are exclusive"
    active = {h: list(range(n)) for h, n in pool.items()}
    if include:
        spec = _parse_filter(include)
        unknown = set(spec) - set(active)
        assert not unknown, f"include references unknown hosts {sorted(unknown)}"
        active = {h: (spec[h] if spec[h] is not None else active[h])
                  for h in active if h in spec}
        for h, slots in active.items():
            bad = set(slots) - set(range(pool[h]))
            assert not bad, f"include slots {sorted(bad)} out of range on {h}"
    elif exclude:
        spec = _parse_filter(exclude)
        unknown = set(spec) - set(active)
        assert not unknown, f"exclude references unknown hosts {sorted(unknown)}"
        for h, slots in spec.items():
            if slots is None:
                active.pop(h, None)
            else:
                bad = set(slots) - set(range(pool[h]))
                assert not bad, f"exclude slots {sorted(bad)} out of range on {h}"
                active[h] = [s for s in active[h] if s not in slots]
                if not active[h]:
                    active.pop(h)
    return active


def encode_world_info(active):
    return base64.urlsafe_b64encode(
        json.dumps(active).encode()).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def build_launch_cmd(args, active, node_rank, master_addr):
    """The per-node spawner command (runs on each host)."""
    return [
        sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
        f"--world_info={encode_world_info(active)}",
        f"--node_rank={node_rank}",
        f"--master_addr={master_addr}",
        f"--master_port={args.master_port}",
        "--", args.user_script, *args.user_args,
    ]


class MultiNodeRunner:
    """Base for remote fan-out backends (reference
    ``multinode_runner.py:47-75``)."""

    def __init__(self, args, active, master_addr, exports=None):
        self.args = args
        self.active = active
        self.master_addr = master_addr
        self.user_exports = dict(exports or {})

    def export_prefix(self):
        """``export K=V; `` prelude for ssh/pdsh remote shells (reference
        ``multinode_runner.py:57-62``)."""
        return "".join(f"export {k}={shlex.quote(v)}; "
                       for k, v in self.user_exports.items())

    def commands(self):
        raise NotImplementedError


class PDSHRunner(MultiNodeRunner):
    name = PDSH_LAUNCHER

    def commands(self):
        hosts = ",".join(self.active.keys())
        # pdsh broadcasts one identical command line; each node passes
        # node_rank=auto and the spawner resolves its rank by matching its
        # hostname against the world info
        cmd = build_launch_cmd(self.args, self.active, "auto", self.master_addr)
        return [["pdsh", "-S", "-f", "1024", "-w", hosts,
                 "{}cd {}; {}".format(self.export_prefix(),
                                      shlex.quote(os.getcwd()),
                                      " ".join(shlex.quote(c) for c in cmd))]]


class SSHRunner(MultiNodeRunner):
    name = SSH_LAUNCHER

    def commands(self):
        cmds = []
        for rank, host in enumerate(self.active):
            cmd = build_launch_cmd(self.args, self.active, rank,
                                   self.master_addr)
            cmds.append(["ssh", host,
                         "{}cd {}; {}".format(
                             self.export_prefix(),
                             shlex.quote(os.getcwd()),
                             " ".join(shlex.quote(c) for c in cmd))])
        return cmds


class MPIRunnerBase(MultiNodeRunner):
    """MPI-scheduled transports (reference ``multinode_runner.py:77-190``).

    Unlike pdsh/ssh, mpirun launches every RANK directly (no per-node
    spawner): the user script runs once per process and
    ``utils/distributed.init_distributed`` resolves its process id/count
    from the MPI environment (``OMPI_COMM_WORLD_RANK`` / ``MV2_COMM_WORLD_
    RANK``) while the coordinator address rides an exported ``DS_*`` var.
    """

    #: env exported to every rank ({} overridden per backend)
    exports = {}

    def __init__(self, args, active, master_addr, exports=None):
        super().__init__(args, active, master_addr, exports)
        self._tmp_files = []
        assert not (args.include or args.exclude), (
            f"{self.name} backend does not support worker include/exclusion "
            "(mpirun owns placement via the hostfile)")

    def backend_exists(self):
        raise NotImplementedError

    def rank_env(self):
        total = sum(len(s) for s in self.active.values())
        # backend defaults < user/.deepspeed_env exports < rendezvous contract
        return {
            **self.exports,
            **self.user_exports,
            ENV_COORDINATOR: f"{self.master_addr}:{self.args.master_port}",
            ENV_NUM_PROCESSES: str(total),
        }

    def _write_hostfile(self, line_fn):
        """A per-invocation hostfile derived from the FILTERED resource set
        (``--num_nodes``/``--num_procs`` trims and the no-hostfile hostname
        fallback must reach mpirun, so the user's raw hostfile path can't be
        passed through).  A mkstemp path, not a fixed /tmp name: concurrent
        launches on one login host must not clobber each other's placement,
        and a fixed world-writable path is a symlink hazard."""
        import tempfile

        fd, path = tempfile.mkstemp(prefix="deepspeed_mpi_hostfile_",
                                    suffix=".txt", text=True)
        with os.fdopen(fd, "w") as f:
            for host, slots in self.active.items():
                f.write(line_fn(host, len(slots)) + "\n")
        self._tmp_files.append(path)
        return path

    def cleanup(self):
        for path in self._tmp_files:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._tmp_files = []


class OpenMPIRunner(MPIRunnerBase):
    name = OPENMPI_LAUNCHER
    exports = {"UCX_TLS": "tcp"}

    def backend_exists(self):
        import shutil

        return shutil.which("ompi_info") is not None

    def commands(self):
        total = sum(len(s) for s in self.active.values())
        hostfile = self._write_hostfile(lambda h, n: f"{h} slots={n}")
        cmd = ["mpirun", "-n", str(total), "-hostfile", hostfile,
               "--mca", "btl", "^openib"]
        for k, v in self.rank_env().items():
            cmd += ["-x", f"{k}={v}"]
        cmd += [sys.executable, "-u", self.args.user_script,
                *self.args.user_args]
        return [cmd]


class MVAPICHRunner(MPIRunnerBase):
    name = MVAPICH_LAUNCHER
    # force TCP-over-IB semantics off; TPU pods rendezvous over plain TCP
    exports = {"MV2_SMP_USE_CMA": "0", "MV2_DEBUG_SHOW_BACKTRACE": "1"}

    def backend_exists(self):
        import shutil

        return shutil.which("mpiname") is not None

    def commands(self):
        counts = [len(s) for s in self.active.values()]
        total = sum(counts)
        assert all(c == counts[0] for c in counts), (
            "mvapich requires the same process count on every node")
        hostfile = self._write_hostfile(lambda h, n: h)
        cmd = ["mpirun", "-np", str(total), "-ppn", str(counts[0]),
               "--hostfile", hostfile]
        for k, v in self.rank_env().items():
            # Hydra's -env consumes TWO tokens: name, value
            cmd += ["-env", k, v]
        cmd += [sys.executable, "-u", self.args.user_script,
                *self.args.user_args]
        return [cmd]


_RUNNERS = {PDSH_LAUNCHER: PDSHRunner, SSH_LAUNCHER: SSHRunner,
            OPENMPI_LAUNCHER: OpenMPIRunner, MVAPICH_LAUNCHER: MVAPICHRunner}


def main(argv=None):
    args = parse_args(argv)
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        assert not (args.include or args.exclude), (
            f"no hostfile at {args.hostfile}; include/exclude need one")
        import socket

        nprocs = args.num_procs if args.num_procs > 0 else DEFAULT_PROCS_PER_NODE
        pool = {socket.gethostname(): nprocs}
    if args.num_nodes > 0:
        pool = dict(list(pool.items())[:args.num_nodes])
    if args.num_procs > 0:
        pool = {h: args.num_procs for h in pool}
    active = filter_resources(pool, args.include, args.exclude)
    assert active, "no hosts left after include/exclude filtering"
    master_addr = args.master_addr or next(iter(active))
    logger.info(f"launching on {active} (coordinator {master_addr}:"
                f"{args.master_port})")

    exports = collect_exports()
    if (len(active) == 1 and not args.force_multi
            and args.launcher in (PDSH_LAUNCHER, SSH_LAUNCHER)):
        cmd = build_launch_cmd(args, active, 0, master_addr)
        # local spawns inherit the env already; merging applies any
        # .deepspeed_env file entries so both paths see the same contract
        result = subprocess.call(cmd, env={**os.environ, **exports})
        sys.exit(result)

    runner = _RUNNERS[args.launcher](args, active, master_addr, exports)
    if isinstance(runner, MPIRunnerBase) and not runner.backend_exists():
        raise RuntimeError(
            f"--launcher={args.launcher} requested but its mpirun toolchain "
            "was not found on PATH")
    try:
        procs = [subprocess.Popen(c) for c in runner.commands()]
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
    finally:
        # temp hostfiles must not leak on Ctrl-C / launch failure either
        if hasattr(runner, "cleanup"):
            runner.cleanup()
    sys.exit(rc)


if __name__ == "__main__":
    main()
