"""Multi-node launcher front-end.

TPU-native analog of the reference ``deepspeed/launcher/runner.py:254-330``:
reads a hostfile, applies ``--include``/``--exclude`` node/slot filters,
encodes the resource map, and either execs the per-node spawner directly
(single node) or fans out over pdsh/ssh (multi node).  The per-process env
contract it establishes (``DS_COORDINATOR``/``DS_NUM_PROCESSES``/
``DS_PROCESS_ID``) is what ``utils/distributed.init_distributed`` feeds to
``jax.distributed.initialize`` — coordinator-based rendezvous instead of
the reference's MASTER_ADDR process groups.

Usage::

    deepspeed [--hostfile H] [--include w1@w2:0,1] [--num_nodes N]
              [--num_procs P] your_script.py --your-args
"""

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys

from ..utils.logging import logger
from .constants import (DEFAULT_HOSTFILE, DEFAULT_MASTER_PORT,
                        DEFAULT_PROCS_PER_NODE, PDSH_LAUNCHER, SSH_LAUNCHER)


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU multi-node launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DEFAULT_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="nodes/slots to include, e.g. "
                             "'worker-0@worker-1:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="nodes/slots to exclude, e.g. 'worker-1:0'")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="cap on node count (first N of the hostfile)")
    parser.add_argument("--num_procs", type=int, default=-1,
                        help="processes per node (default: hostfile slots, "
                             f"or {DEFAULT_PROCS_PER_NODE})")
    parser.add_argument("--master_addr", type=str, default="",
                        help="coordinator address (default: first node)")
    parser.add_argument("--master_port", type=int, default=DEFAULT_MASTER_PORT)
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        choices=[PDSH_LAUNCHER, SSH_LAUNCHER])
    parser.add_argument("--force_multi", action="store_true",
                        help="treat as multi-node even for one host")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path):
    """Parse 'hostname slots=N' lines (reference ``runner.py:115-143``).
    Returns an ordered {hostname: slots} dict; {} when the file is absent
    (single-node fallback)."""
    if not os.path.isfile(path):
        return {}
    pool = {}
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                host, slots = line.split()
                key, n = slots.split("=")
                assert key == "slots"
                n = int(n)
            except Exception as e:
                raise ValueError(f"malformed hostfile line: {line!r}") from e
            if host in pool:
                raise ValueError(f"duplicate host {host!r} in hostfile")
            pool[host] = n
    return pool


def _parse_filter(spec):
    """'w0@w1:0,2' -> {'w0': None, 'w1': [0, 2]} (None = every slot)."""
    out = {}
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host.strip()] = sorted(int(s) for s in slots.split(","))
        else:
            out[part] = None
    return out


def filter_resources(pool, include="", exclude=""):
    """Apply include/exclude filters (reference ``runner.py:146-245``).
    Returns ordered {host: [slot ids]}."""
    assert not (include and exclude), "--include and --exclude are exclusive"
    active = {h: list(range(n)) for h, n in pool.items()}
    if include:
        spec = _parse_filter(include)
        unknown = set(spec) - set(active)
        assert not unknown, f"include references unknown hosts {sorted(unknown)}"
        active = {h: (spec[h] if spec[h] is not None else active[h])
                  for h in active if h in spec}
        for h, slots in active.items():
            bad = set(slots) - set(range(pool[h]))
            assert not bad, f"include slots {sorted(bad)} out of range on {h}"
    elif exclude:
        spec = _parse_filter(exclude)
        unknown = set(spec) - set(active)
        assert not unknown, f"exclude references unknown hosts {sorted(unknown)}"
        for h, slots in spec.items():
            if slots is None:
                active.pop(h, None)
            else:
                bad = set(slots) - set(range(pool[h]))
                assert not bad, f"exclude slots {sorted(bad)} out of range on {h}"
                active[h] = [s for s in active[h] if s not in slots]
                if not active[h]:
                    active.pop(h)
    return active


def encode_world_info(active):
    return base64.urlsafe_b64encode(
        json.dumps(active).encode()).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def build_launch_cmd(args, active, node_rank, master_addr):
    """The per-node spawner command (runs on each host)."""
    return [
        sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
        f"--world_info={encode_world_info(active)}",
        f"--node_rank={node_rank}",
        f"--master_addr={master_addr}",
        f"--master_port={args.master_port}",
        "--", args.user_script, *args.user_args,
    ]


class MultiNodeRunner:
    """Base for remote fan-out backends (reference
    ``multinode_runner.py:47-75``)."""

    def __init__(self, args, active, master_addr):
        self.args = args
        self.active = active
        self.master_addr = master_addr

    def commands(self):
        raise NotImplementedError


class PDSHRunner(MultiNodeRunner):
    name = PDSH_LAUNCHER

    def commands(self):
        hosts = ",".join(self.active.keys())
        # pdsh broadcasts one identical command line; each node passes
        # node_rank=auto and the spawner resolves its rank by matching its
        # hostname against the world info
        cmd = build_launch_cmd(self.args, self.active, "auto", self.master_addr)
        return [["pdsh", "-S", "-f", "1024", "-w", hosts,
                 "cd {}; {}".format(shlex.quote(os.getcwd()),
                                    " ".join(shlex.quote(c) for c in cmd))]]


class SSHRunner(MultiNodeRunner):
    name = SSH_LAUNCHER

    def commands(self):
        cmds = []
        for rank, host in enumerate(self.active):
            cmd = build_launch_cmd(self.args, self.active, rank,
                                   self.master_addr)
            cmds.append(["ssh", host,
                         "cd {}; {}".format(
                             shlex.quote(os.getcwd()),
                             " ".join(shlex.quote(c) for c in cmd))])
        return cmds


def main(argv=None):
    args = parse_args(argv)
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        assert not (args.include or args.exclude), (
            f"no hostfile at {args.hostfile}; include/exclude need one")
        import socket

        nprocs = args.num_procs if args.num_procs > 0 else DEFAULT_PROCS_PER_NODE
        pool = {socket.gethostname(): nprocs}
    if args.num_nodes > 0:
        pool = dict(list(pool.items())[:args.num_nodes])
    if args.num_procs > 0:
        pool = {h: args.num_procs for h in pool}
    active = filter_resources(pool, args.include, args.exclude)
    assert active, "no hosts left after include/exclude filtering"
    master_addr = args.master_addr or next(iter(active))
    logger.info(f"launching on {active} (coordinator {master_addr}:"
                f"{args.master_port})")

    if len(active) == 1 and not args.force_multi:
        cmd = build_launch_cmd(args, active, 0, master_addr)
        result = subprocess.call(cmd)
        sys.exit(result)

    runner = (PDSHRunner if args.launcher == PDSH_LAUNCHER else SSHRunner)(
        args, active, master_addr)
    procs = [subprocess.Popen(c) for c in runner.commands()]
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
