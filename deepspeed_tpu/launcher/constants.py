"""Launcher constants (reference ``deepspeed/launcher/constants.py``)."""

PDSH_LAUNCHER = "pdsh"
SSH_LAUNCHER = "ssh"
OPENMPI_LAUNCHER = "openmpi"
MVAPICH_LAUNCHER = "mvapich"

DEFAULT_HOSTFILE = "/job/hostfile"
DEFAULT_MASTER_PORT = 29500
DEFAULT_PROCS_PER_NODE = 1  # one JAX process drives all local chips

# env contract consumed by utils/distributed.init_distributed (the analog
# of the reference's MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK dance)
ENV_COORDINATOR = "DS_COORDINATOR"
ENV_NUM_PROCESSES = "DS_NUM_PROCESSES"
ENV_PROCESS_ID = "DS_PROCESS_ID"
ENV_LOCAL_RANK = "DS_LOCAL_RANK"
