"""Launcher package: hostfile-driven multi-node job start
(reference ``deepspeed/launcher/``)."""

from .runner import (decode_world_info, encode_world_info, fetch_hostfile,
                     filter_resources)

__all__ = ["decode_world_info", "encode_world_info", "fetch_hostfile",
           "filter_resources"]
