"""Run a command on every host of the hostfile (reference ``bin/ds_ssh``).
Installed as the ``ds_ssh`` console script (see ``pyproject.toml``)."""
import argparse
import shlex
import subprocess
import sys

from deepspeed_tpu.launcher.constants import DEFAULT_HOSTFILE
from deepspeed_tpu.launcher.runner import fetch_hostfile


def main():
    parser = argparse.ArgumentParser(description="run a command on all hosts")
    parser.add_argument("-H", "--hostfile", default=DEFAULT_HOSTFILE)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    assert args.command, "no command given"
    # one quoted command line, identical semantics locally and over ssh
    line = " ".join(shlex.quote(c) for c in args.command)
    pool = fetch_hostfile(args.hostfile) or {"localhost": 1}
    rc = 0
    for host in pool:
        print(f"----- {host} -----")
        if host == "localhost":
            proc = subprocess.run(line, shell=True)
        else:
            proc = subprocess.run(["ssh", host, line])
        rc = rc or proc.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
