"""Per-node process spawner.

TPU-native analog of the reference ``deepspeed/launcher/launch.py:67-167``:
decodes the world info, computes each local process's global id, sets the
``DS_*`` rendezvous env consumed by ``utils/distributed.init_distributed``
(which feeds ``jax.distributed.initialize``), spawns one Python process per
local slot, monitors them, and tears the node down if any child dies.
SIGINT/SIGTERM are forwarded to the children (reference ``:131-146``).

Resilience contract (``deepspeed_tpu/resilience``):

- a child killed by a signal exits the launcher with ``128 + signum``
  (shell convention) and the signal is named in the log — a raw negative
  ``poll()`` code would wrap to a meaningless 24x value;
- ``--max-restarts N`` respawns a failed child up to N times with
  exponential backoff (``DS_RESTART_BACKOFF_SECS``, default 2s, doubling
  per restart of that slot, jittered by ``DS_RESTART_BACKOFF_JITTER`` so
  a fleet of launchers does not re-dial the coordinator in lockstep) —
  pair with ``deepspeed.initialize(..., auto_resume=True)`` so respawns
  land on the last committed checkpoint;
- **poison** exit codes (:data:`POISON_EXIT_CODES`, e.g. a divergence
  abort) never respawn: restarting would replay the same data into the
  same divergence.

Elastic resize-on-failure (``--elastic-config``, ROADMAP item 5): with
an elastic schedule armed, a *respawnable* child death — watchdog exit
85, a signal death, or a SIGTERM preemption notice the child drained its
final save under — no longer respawns the fleet at the same world size.
The supervisor (``elasticity/supervisor.py``) subtracts the failed
capacity from the device budget, asks the HCN planner for the largest
valid world size that still fits, re-derives micro-batch x grad-accum so
the global batch stays on the pre-declared schedule, and respawns the
whole fleet at the new size — sharing the compile cache so the resume is
warm, exporting ``DS_ELASTIC_TARGET_WORLD_SIZE`` so scripts size their
mesh, and ``DEEPSPEED_ELASTICITY_CONFIG`` so the runtime's immutability
check proves every life trains the same schedule.  Poison codes still
tear the node down: a divergence is never "resized around".

Integrity-directed eviction (``resilience/integrity.py``): a child death
that carries an integrity verdict — exit 87 from a fingerprint-consensus
outlier or a hang-quorum fire, with the detecting rank's verdict file in
the shared run dir — turns the blind resize into an *aimed* one.  The
supervisor reads the verdict, charges the suspect's devices against the
elastic budget, blocklists the suspect's slot (``EvictionLedger``) so
the bad host never rejoins the fleet, clears the run dir's fleet state
(a new life must not vote against the previous life's stale
fingerprints), and respawns the fleet around the eviction; every rank
rolls back to the latest committed checkpoint via ``auto_resume``.
Verdicts past the eviction budget (``DS_INTEGRITY_MAX_EVICTIONS``,
default 1) poison the run instead: a fleet that keeps indicting ranks
after an eviction already removed the suspect has a problem no resize
fixes.
"""

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import time

from ..elasticity.config import (ElasticityError,
                                 ElasticityIncompatibleWorldSize)
from ..elasticity.constants import ELASTICITY
from ..elasticity.supervisor import (EvictionLedger, export_plan_env,
                                     plan_world_size)
from ..resilience import integrity as fleet_integrity
from ..resilience.constants import (EXIT_DIVERGENCE_ABORT,
                                    EXIT_INTEGRITY_EVICT,
                                    POISON_EXIT_CODES)
# stdlib-only import chain on purpose: the launcher must not need jax
# (the elasticity planner/supervisor above are plain-python too)
from ..telemetry.events import (EVENT_ELASTIC, EVENT_PROC_EXIT,
                                EVENT_PROC_RESPAWN, EVENT_PROC_SPAWN,
                                EVENT_RUN_END, EventLog)
from ..utils.logging import logger
from .constants import (ENV_COORDINATOR, ENV_LOCAL_RANK, ENV_NUM_PROCESSES,
                        ENV_PROCESS_ID)
from .runner import decode_world_info


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="DeepSpeed-TPU node spawner")
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=str, default="0",
                        help="this node's index, or 'auto' (match hostname)")
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, required=True)
    parser.add_argument("--max-restarts", "--max_restarts", type=int,
                        default=0, dest="max_restarts",
                        help="respawn a failed child up to N times with "
                             "backoff (poison exit codes never respawn)")
    parser.add_argument("--telemetry-dir", "--telemetry_dir", type=str,
                        default=os.environ.get("DS_TELEMETRY_DIR", ""),
                        dest="telemetry_dir",
                        help="telemetry run dir: spawn/exit/respawn events "
                             "land in events-launcher.jsonl there (point "
                             "it at the engines' telemetry.run_dir so the "
                             "report CLI merges one timeline)")
    parser.add_argument("--compile-cache-dir", "--compile_cache_dir",
                        type=str,
                        default=os.environ.get("DS_COMPILE_CACHE_DIR", ""),
                        dest="compile_cache_dir",
                        help="persistent XLA compile cache for the children "
                             "(exported as JAX_COMPILATION_CACHE_DIR): a "
                             "--max-restarts respawn then warm-starts its "
                             "programs from here instead of recompiling — "
                             "stdlib-only on this side, jax reads the env "
                             "var natively in the child")
    parser.add_argument("--elastic-config", "--elastic_config", type=str,
                        default=os.environ.get("DS_ELASTIC_CONFIG", ""),
                        dest="elastic_config",
                        help="json file (a ds_config with an 'elasticity' "
                             "block, or a bare elasticity block) arming "
                             "elastic resize-on-failure: respawnable child "
                             "deaths re-plan the world size via the HCN "
                             "planner instead of respawning at the same "
                             "size")
    parser.add_argument("--elastic-devices", "--elastic_devices", type=int,
                        default=int(os.environ.get("DS_ELASTIC_DEVICES",
                                                   "0")),
                        dest="elastic_devices",
                        help="initial accelerator budget for the elastic "
                             "supervisor (default: one device per slot); "
                             "each respawnable failure subtracts "
                             "DS_ELASTIC_DEVICES_PER_FAILURE (default: "
                             "devices/processes) before re-planning")
    parser.add_argument("training_script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = parser.parse_args(args)
    # tolerate the '--' separator the runner inserts
    if ns.training_script == "--" and ns.script_args:
        ns.training_script = ns.script_args[0]
        ns.script_args = ns.script_args[1:]
    return ns


def map_exit_code(ret):
    """Normalize ``Popen.poll()``'s return into a shell-meaningful exit
    code: signal deaths (negative) map to ``128 + signum``.  Returns
    ``(code, signal_name_or_None)``."""
    if ret is None or ret >= 0:
        return ret, None
    signum = -ret
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = f"signal {signum}"
    return 128 + signum, name


def load_elastic_config(path):
    """Read the ``elasticity`` block from ``path`` — a full ds_config
    json or a bare elasticity block — and require it enabled (an armed
    supervisor with a disabled schedule is a config error, not a silent
    no-op)."""
    with open(path) as f:
        cfg = json.load(f)
    block = cfg.get(ELASTICITY, cfg) if isinstance(cfg, dict) else None
    if not isinstance(block, dict):
        raise ValueError(f"--elastic-config {path}: expected a json object")
    if not block.get("enabled", False):
        raise ValueError(
            f"--elastic-config {path}: elasticity block is not enabled "
            "('enabled': true required to arm resize-on-failure)")
    return block


def backoff_jitter():
    """Multiplicative backoff jitter factor in [1, 1+DS_RESTART_BACKOFF_
    JITTER] (default 0.25): desynchronizes a fleet of launchers that all
    lost children to the same event, so the coordinator is not re-dialed
    in lockstep."""
    jitter = float(os.environ.get("DS_RESTART_BACKOFF_JITTER", "0.25"))
    return 1.0 + max(0.0, jitter) * random.random()


def resolve_node_rank(node_rank, world):
    if node_rank != "auto":
        return int(node_rank)
    hostname = socket.gethostname()
    hosts = list(world.keys())
    for cand in (hostname, hostname.split(".")[0], "localhost"):
        if cand in hosts:
            return hosts.index(cand)
    raise RuntimeError(
        f"cannot resolve node rank: hostname {hostname!r} not in {hosts}")


def main(argv=None):
    args = parse_args(argv)
    world = decode_world_info(args.world_info)
    node_rank = resolve_node_rank(args.node_rank, world)
    hosts = list(world.keys())
    assert 0 <= node_rank < len(hosts), f"node_rank {node_rank} vs {hosts}"

    # global process ids: hostfile order, then slot order
    first_id = sum(len(world[h]) for h in hosts[:node_rank])
    local_slots = world[hosts[node_rank]]
    total = sum(len(v) for v in world.values())

    # structured telemetry: restarts and exit codes become queryable
    # events instead of log lines (report CLI merges this stream with the
    # training ranks' events when they share a run dir)
    tel = (EventLog(args.telemetry_dir, rank="launcher",
                    filename="events-launcher.jsonl")
           if args.telemetry_dir else None)

    def tel_emit(event_type, **data):
        if tel is not None:
            tel.emit(event_type, **data)

    # -- elastic supervisor state (resize-on-failure; tentpole of the
    # preemptible-fleet story).  Armed by --elastic-config; the initial
    # world size ALSO comes from the planner so the first life and every
    # resized life share one derivation path.
    elastic = None
    if args.elastic_config:
        if len(hosts) > 1:
            raise RuntimeError(
                "--elastic-config: elastic resize-on-failure currently "
                "supervises a single-node fleet (one spawner owns the "
                "whole respawn decision); multi-node resize needs a "
                "cross-node supervisor")
        elastic_dict = load_elastic_config(args.elastic_config)
        budget = args.elastic_devices or len(local_slots)
        per_failure = int(os.environ.get(
            "DS_ELASTIC_DEVICES_PER_FAILURE",
            str(max(1, budget // max(1, len(local_slots))))))
        plan = plan_world_size(elastic_dict, budget)
        elastic = {"dict": elastic_dict, "budget": budget,
                   "per_failure": per_failure, "plan": plan, "resizes": 0,
                   "ledger": EvictionLedger()}
        # the FIRST life is also sized by the planner: processes scale
        # with the planned world size exactly as resizes do (a schedule
        # whose largest valid world is below the slot count must not
        # spawn extra ranks that own no mesh slice)
        n0 = min(len(local_slots),
                 max(1, round(len(local_slots) * plan.world_size
                              / max(1, budget))))
        local_slots = local_slots[:n0]
        total = n0
        logger.info(
            f"elastic supervisor armed: budget {budget} device(s), "
            f"world_size {plan.world_size} over {n0} process(es), "
            f"{per_failure} device(s) charged per failure")

    def spawn_env(local_rank, slot, n_procs):
        env = os.environ.copy()
        if args.compile_cache_dir:
            # warm-start contract for respawns: the child (and every
            # respawn of it) compiles into / loads from one shared cache
            env["JAX_COMPILATION_CACHE_DIR"] = os.path.abspath(
                args.compile_cache_dir)
        if args.telemetry_dir:
            # every rank's engine defaults its telemetry run_dir here
            # (telemetry/config.py reads DS_TELEMETRY_DIR), so the
            # launcher's events-launcher.jsonl, the ranks' events/
            # metrics, AND the per-rank latency-rank<k>.json skew
            # exchange all share one directory — the report CLI merges
            # one timeline and cross-rank skew needs no other channel
            env["DS_TELEMETRY_DIR"] = os.path.abspath(args.telemetry_dir)
        env[ENV_COORDINATOR] = f"{args.master_addr}:{args.master_port}"
        env[ENV_NUM_PROCESSES] = str(n_procs)
        env[ENV_PROCESS_ID] = str(first_id + local_rank)
        # the SLOT id from the (include/exclude-filtered) hostfile, so slot
        # filtering reaches the process; device binding from it is
        # platform-specific (e.g. TPU_VISIBLE_CHIPS), left to the script
        env[ENV_LOCAL_RANK] = str(slot)
        if elastic is not None:
            # the planned world size + normalized schedule travel to the
            # child: scripts size their mesh from the former, the
            # runtime's ensure_immutable_elastic_config proves the
            # latter never drifted across respawns
            export_plan_env(env, elastic["dict"], elastic["plan"])
        return env

    def spawn_fleet(slots, n_procs, restart=None):
        fleet = []
        for local_rank, slot in enumerate(slots):
            env = spawn_env(local_rank, slot, n_procs)
            cmd = [sys.executable, "-u", args.training_script,
                   *args.script_args]
            logger.info(
                f"launching process {first_id + local_rank}/{n_procs}: "
                f"{' '.join(cmd)}")
            fleet.append({"proc": subprocess.Popen(cmd, env=env),
                          "cmd": cmd, "env": env, "slot": slot,
                          "rank": first_id + local_rank, "restarts": 0,
                          "respawn_at": None})
            tel_emit(EVENT_PROC_SPAWN, proc_rank=first_id + local_rank,
                     pid=fleet[-1]["proc"].pid,
                     **({} if restart is None else {"restart": restart}))
        return fleet

    if args.telemetry_dir:
        # a reused run dir may hold a PREVIOUS run's verdict (teardown
        # paths don't clear — the launcher is already exiting) plus its
        # fingerprints/heartbeats: consumed at this run's first
        # respawnable death they would blocklist an innocent slot and
        # burn the eviction budget.  This run starts from a clean
        # integrity plane.  (Multi-node: a late-starting node's clear
        # briefly thins the live fleet's files; they republish within
        # one beat/print cadence.)
        n_stale = fleet_integrity.clear_fleet_state(args.telemetry_dir)
        if n_stale:
            logger.info(f"cleared {n_stale} stale integrity-plane "
                        "file(s) left in the run dir by a previous run")

    children = spawn_fleet(local_slots, total)   # [{proc, cmd, env, ...}]

    # Children may install a preemption checkpoint hook (checkpoint
    # subsystem, "save_on_preemption") that drains one final synchronous
    # save on SIGTERM — give them a grace window before escalating to
    # SIGKILL so that save can land.
    grace_secs = float(os.environ.get("DS_TERM_GRACE_SECS", "30"))

    def live_procs():
        return [c["proc"] for c in children if c["proc"] is not None]

    def terminate_all(sig=signal.SIGTERM, grace=grace_secs):
        for p in live_procs():
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except ProcessLookupError:
                    pass
        deadline = time.time() + grace
        while (time.time() < deadline
               and any(p.poll() is None for p in live_procs())):
            time.sleep(0.1)
        for p in live_procs():
            if p.poll() is None:
                logger.warning(f"process {p.pid} survived {grace:.0f}s "
                               "grace after signal; killing")
                p.kill()

    def tel_end(reason):
        # terminal marker for the launcher stream; reached from BOTH the
        # normal monitor-loop exit and the signal path (sys.exit there
        # would otherwise skip the end-of-main emit and the merged report
        # would read a clean preemption as a crashed launcher)
        if tel is not None:
            tel.emit(EVENT_RUN_END, reason=reason)
            tel.close()

    def forward_signal(signum, _frame):
        # the long grace exists for the SIGTERM preemption-save path; a
        # Ctrl-C should not pin the launcher for 30s (and a second Ctrl-C
        # escalates straight to SIGKILL via the nested handler's 0 grace)
        if signum == signal.SIGINT:
            signal.signal(signal.SIGINT,
                          lambda s, f: terminate_all(s, grace=0.0))
            terminate_all(signum, grace=min(grace_secs, 2.0))
        else:
            terminate_all(signum)
        tel_end(f"launcher signal {signum}")
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, forward_signal)
    signal.signal(signal.SIGTERM, forward_signal)

    consumed_verdicts = set()

    def consume_integrity_verdict(code):
        """The integrity verdict behind a child death, if any.  An exit
        87 should always have one (the detecting rank commits the
        verdict file before exiting); every OTHER respawnable death also
        checks, because the first death the monitor observes need not be
        the detecting rank (a hang victim dies by signal in the drain
        while its accusers exit 87).  Falls back to the CONSUMED marker
        a sibling node's launcher renamed the verdict to (multi-node
        shared run dir: deleting on first consumption would race the
        siblings' monitor polls and the node that owns the suspect's
        slot would resize blind); each verdict — identified by its
        commit (ts, suspect, kind) — is acted on at most once per
        launcher."""
        if not args.telemetry_dir:
            return None
        verdict = fleet_integrity.read_verdict(args.telemetry_dir,
                                               include_consumed=True)
        if verdict is not None:
            key = (verdict.get("ts"), verdict.get("suspect"),
                   verdict.get("kind"))
            if key in consumed_verdicts:
                verdict = None          # already acted on this one
            else:
                consumed_verdicts.add(key)
                # free VERDICT_FILE for the next life's first-writer-
                # wins commit while leaving the marker for siblings
                fleet_integrity.mark_verdict_consumed(args.telemetry_dir)
        if verdict is None and code == EXIT_INTEGRITY_EVICT:
            logger.warning(
                f"exit {code} (integrity eviction) without a readable "
                "verdict file in the run dir; resizing blind")
        return verdict

    def clear_integrity_state(reason, rank=None, keep_consumed=False):
        """Fleet state (fingerprints, heartbeats, the consumed verdict)
        must not leak into the next life: a rolled-back fleet recomputes
        the abandoned timeline and must not be voted against by its
        previous self.  ``rank`` narrows the clear to one rank's files
        (ordinary single-rank respawn: peers' state stays valid);
        ``keep_consumed`` preserves the consumed-verdict marker for
        sibling nodes' launchers (the resize path)."""
        if args.telemetry_dir:
            n = fleet_integrity.clear_fleet_state(
                args.telemetry_dir, rank=rank,
                keep_consumed=keep_consumed)
            if n:
                logger.info(f"cleared {n} integrity-plane file(s) from "
                            f"the run dir ({reason})")

    def elastic_resize(child, code, signame, verdict=None):
        """One resize cycle: charge the failed capacity, re-plan, drain
        the survivors (SIGTERM grace — their preemption saves land),
        respawn the whole fleet at the planned size.  With an integrity
        ``verdict``, the resize is aimed: the suspect's slot joins the
        eviction blocklist and never rejoins the fleet.  Returns the new
        children list, None when no valid world size is left, or
        ``"poison"`` when a repeated eviction must tear the run down
        un-respawned."""
        suspect_slot = None
        if verdict is not None:
            suspect = verdict.get("suspect")
            suspect_slot = next((c["slot"] for c in children
                                 if c["rank"] == suspect), None)
            tel_emit(EVENT_ELASTIC, phase="evict", suspect=suspect,
                     slot=suspect_slot, kind=verdict.get("kind"),
                     detail=verdict.get("detail"),
                     eviction=len(elastic["ledger"].evictions) + 1,
                     exit_code=code)
            if not elastic["ledger"].record(suspect, suspect_slot,
                                            verdict.get("kind", "?"),
                                            verdict.get("detail", "")):
                return "poison"
        elastic["resizes"] += 1
        elastic["budget"] -= elastic["per_failure"]
        prev = elastic["plan"]
        try:
            plan = plan_world_size(elastic["dict"], elastic["budget"])
        except ElasticityIncompatibleWorldSize as e:
            logger.error(f"elastic resize: {e}; tearing the node down")
            return None
        # a SIGTERM death is read as a preemption notice: the child's
        # grace-window save (checkpoint.save_on_preemption) already
        # landed, so the resized fleet resumes from it warm
        trigger = (f"integrity eviction (rank {verdict.get('suspect')}, "
                   f"{verdict.get('kind')})" if verdict is not None else
                   f"preemption notice ({signame})"
                   if signame == "SIGTERM" else
                   f"signal death ({signame})" if signame else
                   f"exit code {code}")
        tel_emit(EVENT_ELASTIC, phase="plan",
                 surviving_devices=elastic["budget"],
                 prev_world_size=prev.world_size,
                 planned_world_size=plan.world_size,
                 micro_batch=plan.micro_batch,
                 grad_accum=plan.grad_accum,
                 global_batch=plan.global_batch,
                 trigger=trigger, exit_code=code)
        delay = (backoff_base * (2 ** (elastic["resizes"] - 1))
                 * backoff_jitter())
        # the respawn event carries the PLANNED world size: a reader of
        # the launcher stream alone can see the fleet shrank, without
        # joining against the engines' streams
        tel_emit(EVENT_PROC_RESPAWN, proc_rank=child["rank"],
                 restart=elastic["resizes"], backoff_secs=delay,
                 exit_code=code, planned_world_size=plan.world_size)
        logger.warning(
            f"elastic resize {elastic['resizes']}/{args.max_restarts}: "
            f"{trigger} -> world {prev.world_size} -> {plan.world_size} "
            f"(micro={plan.micro_batch} x accum={plan.grad_accum}), "
            f"respawning after {delay:.1f}s backoff")
        # drain survivors under the SIGTERM grace before respawning: the
        # fleet must not straddle two world sizes, and in-flight saves
        # must commit before their writers die
        terminate_all()
        time.sleep(delay)
        # the new life rolls back to the latest committed checkpoint
        # (auto_resume) and recomputes the abandoned timeline — stale
        # fingerprints/heartbeats must go first; the consumed-verdict
        # marker stays (siblings sharing the run dir dedup by ts)
        clear_integrity_state(f"resize {elastic['resizes']}",
                              keep_consumed=True)
        n_prev = max(1, len(children))
        n_procs = max(1, round(n_prev * plan.world_size
                               / max(1, prev.world_size)))
        # spawn only from slots no integrity verdict has indicted: the
        # evicted host's devices never rejoin the fleet
        slots = elastic["ledger"].filter_slots(local_slots)
        if not slots:
            logger.error("elastic resize: every slot is on the eviction "
                         "blocklist; tearing the node down")
            return None
        n_procs = min(n_procs, len(slots))
        elastic["plan"] = plan
        fleet = spawn_fleet(slots[:n_procs], n_procs,
                            restart=elastic["resizes"])
        tel_emit(EVENT_ELASTIC, phase="resize", procs=n_procs,
                 world_size=plan.world_size, restart=elastic["resizes"],
                 **({"evicted_slots": sorted(
                     elastic["ledger"].blocked_slots)}
                    if elastic["ledger"].evictions else {}))
        return fleet

    # monitor: a failed child is respawned (up to --max-restarts, with
    # jittered exponential backoff) unless its exit code is poison;
    # with the elastic supervisor armed the respawn becomes a fleet
    # RESIZE; anything past the budget tears down the node (reference
    # :151-167)
    backoff_base = float(os.environ.get("DS_RESTART_BACKOFF_SECS", "2"))
    alive = list(children)
    rc = 0
    tearing_down = False
    while alive:
        time.sleep(float(os.environ.get("DS_MONITOR_POLL_SECS", "1")))
        for child in list(alive):
            if child["proc"] is None:
                # backoff window: the respawn deadline is checked per poll
                # tick instead of sleeping inline, so a sibling's poison
                # exit or signal death still tears the node down promptly
                if tearing_down:
                    alive.remove(child)
                elif time.time() >= child["respawn_at"]:
                    child["respawn_at"] = None
                    child["proc"] = subprocess.Popen(child["cmd"],
                                                     env=child["env"])
                    tel_emit(EVENT_PROC_SPAWN, proc_rank=child["rank"],
                             pid=child["proc"].pid,
                             restart=child["restarts"])
                continue
            ret = child["proc"].poll()
            if ret is None:
                continue
            code, signame = map_exit_code(ret)
            tel_emit(EVENT_PROC_EXIT, proc_rank=child["rank"], code=code,
                     signal=signame)
            if code == 0:
                alive.remove(child)
                continue
            where = (f"process {child['proc'].pid} (rank {child['rank']})")
            if signame is not None:
                logger.error(f"{where} killed by {signame}; exit code "
                             f"mapped to {code}")
            if code in POISON_EXIT_CODES:
                # a divergence abort is never "resized around": replaying
                # the same data on a smaller fleet reaches the same
                # divergence with less capacity
                logger.error(
                    f"{where} exited with poison code {code} (e.g. "
                    "divergence abort): never respawning — terminating "
                    "the node")
            elif (elastic is not None and not tearing_down
                    and elastic["resizes"] < args.max_restarts):
                fleet = elastic_resize(child, code, signame,
                                       verdict=consume_integrity_verdict(
                                           code))
                if fleet == "poison":
                    # repeated eviction: escalate to the poison code —
                    # the teardown below must never respawn, and the
                    # launcher's own exit says why
                    code = EXIT_DIVERGENCE_ABORT
                elif fleet is not None:
                    children = fleet
                    alive = list(children)
                    break   # the fleet was replaced wholesale
            elif (elastic is None and not tearing_down
                    and child["restarts"] < args.max_restarts):
                child["restarts"] += 1
                delay = (backoff_base * (2 ** (child["restarts"] - 1))
                         * backoff_jitter())
                logger.warning(
                    f"{where} exited with code {code}; respawning "
                    f"(restart {child['restarts']}/{args.max_restarts}) "
                    f"after {delay:.1f}s backoff")
                tel_emit(EVENT_PROC_RESPAWN, proc_rank=child["rank"],
                         restart=child["restarts"], backoff_secs=delay,
                         exit_code=code)
                if code == EXIT_INTEGRITY_EVICT:
                    # no supervisor to aim the respawn, but the new life
                    # still must not vote against its previous self's
                    # stale fingerprints/heartbeats
                    clear_integrity_state(
                        f"respawn of rank {child['rank']}")
                else:
                    # ordinary crash: the dead life's stale heartbeat
                    # would read as a hang (step lags the head, beat
                    # stale) through the backoff + re-init window and
                    # the quorum would falsely evict the new life —
                    # clear only THIS rank's files, peers' state is
                    # still valid
                    clear_integrity_state(
                        f"respawn of rank {child['rank']}",
                        rank=child["rank"])
                child["proc"] = None
                child["respawn_at"] = time.time() + delay
                continue
            else:
                logger.error(f"{where} exited with code {code}; "
                             "terminating remaining processes")
            alive.remove(child)
            tearing_down = True
            terminate_all()
            if rc == 0:  # keep the FIRST failure, not siblings' SIGTERM
                rc = code
    tel_end(f"launcher exit rc={rc}")
    sys.exit(rc)


if __name__ == "__main__":
    main()
