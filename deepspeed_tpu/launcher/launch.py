"""Per-node process spawner.

TPU-native analog of the reference ``deepspeed/launcher/launch.py:67-167``:
decodes the world info, computes each local process's global id, sets the
``DS_*`` rendezvous env consumed by ``utils/distributed.init_distributed``
(which feeds ``jax.distributed.initialize``), spawns one Python process per
local slot, monitors them, and tears the node down if any child dies.
SIGINT/SIGTERM are forwarded to the children (reference ``:131-146``).
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

from ..utils.logging import logger
from .constants import (ENV_COORDINATOR, ENV_LOCAL_RANK, ENV_NUM_PROCESSES,
                        ENV_PROCESS_ID)
from .runner import decode_world_info


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="DeepSpeed-TPU node spawner")
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=str, default="0",
                        help="this node's index, or 'auto' (match hostname)")
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, required=True)
    parser.add_argument("training_script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = parser.parse_args(args)
    # tolerate the '--' separator the runner inserts
    if ns.training_script == "--" and ns.script_args:
        ns.training_script = ns.script_args[0]
        ns.script_args = ns.script_args[1:]
    return ns


def resolve_node_rank(node_rank, world):
    if node_rank != "auto":
        return int(node_rank)
    hostname = socket.gethostname()
    hosts = list(world.keys())
    for cand in (hostname, hostname.split(".")[0], "localhost"):
        if cand in hosts:
            return hosts.index(cand)
    raise RuntimeError(
        f"cannot resolve node rank: hostname {hostname!r} not in {hosts}")


def main(argv=None):
    args = parse_args(argv)
    world = decode_world_info(args.world_info)
    node_rank = resolve_node_rank(args.node_rank, world)
    hosts = list(world.keys())
    assert 0 <= node_rank < len(hosts), f"node_rank {node_rank} vs {hosts}"

    # global process ids: hostfile order, then slot order
    first_id = sum(len(world[h]) for h in hosts[:node_rank])
    local_slots = world[hosts[node_rank]]
    total = sum(len(v) for v in world.values())

    procs = []
    for local_rank, slot in enumerate(local_slots):
        env = os.environ.copy()
        env[ENV_COORDINATOR] = f"{args.master_addr}:{args.master_port}"
        env[ENV_NUM_PROCESSES] = str(total)
        env[ENV_PROCESS_ID] = str(first_id + local_rank)
        # the SLOT id from the (include/exclude-filtered) hostfile, so slot
        # filtering reaches the process; device binding from it is
        # platform-specific (e.g. TPU_VISIBLE_CHIPS), left to the script
        env[ENV_LOCAL_RANK] = str(slot)
        cmd = [sys.executable, "-u", args.training_script, *args.script_args]
        logger.info(f"launching process {first_id + local_rank}/{total}: "
                    f"{' '.join(cmd)}")
        procs.append(subprocess.Popen(cmd, env=env))

    # Children may install a preemption checkpoint hook (checkpoint
    # subsystem, "save_on_preemption") that drains one final synchronous
    # save on SIGTERM — give them a grace window before escalating to
    # SIGKILL so that save can land.
    grace_secs = float(os.environ.get("DS_TERM_GRACE_SECS", "30"))

    def terminate_all(sig=signal.SIGTERM, grace=grace_secs):
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except ProcessLookupError:
                    pass
        deadline = time.time() + grace
        while (time.time() < deadline
               and any(p.poll() is None for p in procs)):
            time.sleep(0.1)
        for p in procs:
            if p.poll() is None:
                logger.warning(f"process {p.pid} survived {grace:.0f}s "
                               "grace after signal; killing")
                p.kill()

    def forward_signal(signum, _frame):
        # the long grace exists for the SIGTERM preemption-save path; a
        # Ctrl-C should not pin the launcher for 30s (and a second Ctrl-C
        # escalates straight to SIGKILL via the nested handler's 0 grace)
        if signum == signal.SIGINT:
            signal.signal(signal.SIGINT,
                          lambda s, f: terminate_all(s, grace=0.0))
            terminate_all(signum, grace=min(grace_secs, 2.0))
        else:
            terminate_all(signum)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, forward_signal)
    signal.signal(signal.SIGTERM, forward_signal)

    # monitor: any child failure tears down the node (reference :151-167)
    alive = list(procs)
    rc = 0
    while alive:
        time.sleep(1)
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                logger.error(f"process {p.pid} exited with code {ret}; "
                             "terminating remaining processes")
                terminate_all()
                if rc == 0:  # keep the FIRST failure, not siblings' SIGTERM
                    rc = ret
    sys.exit(rc)


if __name__ == "__main__":
    main()
