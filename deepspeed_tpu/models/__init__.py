from .bert import (BertConfig, BertForPreTrainingTPU,
                   BertForQuestionAnsweringTPU,
                   BertForSequenceClassificationTPU, BertModel)
from .gpt2 import GPT2Config, GPT2LMHeadTPU
from .layers import TransformerLayer, cross_entropy_with_logits
from .moe import MoEFFN, MoETransformerLayer
