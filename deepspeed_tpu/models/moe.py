"""Mixture-of-Experts layer with expert parallelism over the ``expert`` axis.

Beyond-reference capability (the reference predates MoE — SURVEY §2.5 lists
EP as absent): a top-k routed expert FFN whose experts shard over the
``expert`` mesh axis.  Written GSPMD-style: dispatch and combine are
einsums against a routing tensor, with sharding constraints on the
expert-major intermediates — XLA inserts the all-to-all over ICI, exactly
as it inserts ZeRO's reduce-scatters.  No hand-written collective, no
uneven shapes (capacity is static, overflow tokens fall back to the
residual stream).

Routing is *grouped* per sequence (GShard-style): each batch row routes its
own S tokens with capacity ``ceil(k · S / E · capacity_factor)``, so the
dispatch/combine tensors are [B, S, E, C] with C ∝ S/E — linear in total
tokens — instead of the quadratic [T, E, k·T/E] a global route would cost.

Router: top-k gating with the Switch-Transformer load-balancing auxiliary
loss ``E · Σ_e fraction_e · mean_prob_e``.  Top-1 keeps the raw gate
probability as the combine weight (Switch semantics — renormalizing a
single weight to 1 would starve the router of task-loss gradient); top-k>1
renormalizes over the selected experts (GShard semantics).
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import get_current_mesh
from .layers import TransformerLayer, dense, dropout, gelu, layer_norm


def _constrain_expert(t, spec):
    """Sharding constraint against the engine's current mesh; a no-op
    outside an engine/mesh context (plain single-device model calls)."""
    mesh = get_current_mesh()
    if mesh is not None and "expert" in mesh.axis_names:
        return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, spec))
    return t


def _router_dispatch(probs, k, capacity):
    """Routing tensors for ONE group from its gate probabilities.

    probs: [T, E] fp32 softmax.  Returns ``(dispatch [T, E, C] bool,
    combine [T, E, C] fp32, aux_loss scalar)``.
    """
    T, E = probs.shape
    gates = []  # (weight [T], index [T]) per choice
    masked = probs
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        w = jnp.take_along_axis(masked, idx[:, None], axis=-1)[:, 0]
        gates.append((w, idx))
        masked = masked * (1.0 - jax.nn.one_hot(idx, E, dtype=probs.dtype))

    if k > 1:
        # GShard: kept tokens combine to weight ~1 across their k experts
        total = sum(w for w, _ in gates) + 1e-9
        gates = [(w / total, idx) for w, idx in gates]
    # k == 1 keeps the raw gate probability (Switch): scaling the expert
    # output by the prob is what feeds task-loss gradient to the router

    dispatch = jnp.zeros((T, E, capacity), bool)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # running per-expert fill count, so later choices queue behind earlier
    fill = jnp.zeros((E,), jnp.int32)
    for w, idx in gates:
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)  # [T, E]
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot  # [T, E]
        pos = jnp.sum(pos_in_expert, axis=-1) + fill[idx]  # [T]
        keep = pos < capacity
        slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [T, C]
        contrib = (onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
                   * keep.astype(jnp.float32)[:, None, None])
        dispatch = dispatch | (contrib > 0.0)
        combine = combine + contrib * w[:, None, None]
        fill = fill + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)

    # Switch load-balancing loss on the FIRST choice distribution
    first_idx = gates[0][1]
    fraction = jnp.mean(jax.nn.one_hot(first_idx, E, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(fraction * mean_prob)
    return dispatch, combine, aux


class MoEFFN:
    """Routed expert FFN: x [B, S, H] → (y [B, S, H], aux_loss).

    Expert parameters carry a leading ``num_experts`` dim sharded over
    ``expert``; tokens that overflow an expert's per-group capacity
    contribute zero here and survive through the residual connection.
    """

    def __init__(self, hidden_size, intermediate_size, num_experts, k=2,
                 capacity_factor=1.25, initializer_range=0.02):
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.k = min(k, num_experts)
        self.capacity_factor = capacity_factor
        self.initializer_range = initializer_range

    def init(self, rng):
        kr, k1, k2 = jax.random.split(rng, 3)
        E, H, I = self.num_experts, self.hidden_size, self.intermediate_size
        s = self.initializer_range
        return {
            "router": {"kernel": jax.random.normal(kr, (H, E), jnp.float32) * s},
            "fc1": {"kernel": jax.random.normal(k1, (E, H, I), jnp.float32) * s,
                    "bias": jnp.zeros((E, I), jnp.float32)},
            "fc2": {"kernel": jax.random.normal(k2, (E, I, H), jnp.float32) * s,
                    "bias": jnp.zeros((E, H), jnp.float32)},
        }

    @staticmethod
    def partition_specs():
        return {"router": {"kernel": P()},
                "fc1": {"kernel": P("expert", None, "model"),
                        "bias": P("expert", "model")},
                "fc2": {"kernel": P("expert", "model", None),
                        "bias": P("expert")}}

    def capacity(self, group_tokens):
        cap = int(math.ceil(self.k * group_tokens / self.num_experts
                            * self.capacity_factor))
        # pad to a sublane multiple so expert blocks tile cleanly
        return max(8, ((cap + 7) // 8) * 8)

    def apply(self, params, x):
        B, S, H = x.shape
        E, C = self.num_experts, self.capacity(S)

        logits = (x.astype(jnp.float32)
                  @ params["router"]["kernel"])  # [B, S, E] fp32 routing
        probs = jax.nn.softmax(logits, axis=-1)
        # grouped routing: each sequence routes independently
        dispatch, combine, aux = jax.vmap(
            lambda p: _router_dispatch(p, self.k, C))(probs)
        aux = jnp.mean(aux)

        # expert-major dispatch with the group dim along for the ride; the
        # sharding constraint makes XLA move token blocks to their expert's
        # devices (all-to-all over ICI)
        expert_in = jnp.einsum("bsec,bsh->bech", dispatch.astype(x.dtype), x)
        expert_in = _constrain_expert(expert_in, P(None, "expert", None, None))
        h = gelu(jnp.einsum("bech,ehi->beci", expert_in,
                            params["fc1"]["kernel"].astype(x.dtype))
                 + params["fc1"]["bias"].astype(x.dtype)[None, :, None, :])
        out_e = (jnp.einsum("beci,eih->bech", h,
                            params["fc2"]["kernel"].astype(x.dtype))
                 + params["fc2"]["bias"].astype(x.dtype)[None, :, None, :])
        out_e = _constrain_expert(out_e, P(None, "expert", None, None))
        y = jnp.einsum("bsec,bech->bsh", combine.astype(x.dtype), out_e)
        return y, aux


class MoETransformerLayer:
    """Pre-LN decoder/encoder block with a routed-expert FFN.

    The attention half IS a :class:`TransformerLayer` (shared
    ``attention_core`` plus its init/partition specs for the attention
    parameters), so ``attn_impl``/``sparsity_config`` and the memory knobs
    behave identically in dense and MoE blocks.  ``apply`` returns
    ``(y, aux_loss)`` — the model adds ``moe_aux_coef · mean(aux)`` to its
    training objective.
    """

    _ATTN_PARAM_KEYS = ("qkv", "attn_out", "ln_attn", "ln_mlp")

    def __init__(self, hidden_size, heads, num_experts, intermediate_size=None,
                 causal=True, k=2, capacity_factor=1.25,
                 attn_dropout_ratio=0.1, hidden_dropout_ratio=0.1,
                 initializer_range=0.02, layer_norm_eps=1e-5,
                 attn_impl="auto", sparsity_config=None,
                 gelu_checkpoint=False, attn_dropout_checkpoint=False,
                 normalize_invertible=False):
        self.hidden_size = hidden_size
        self.hidden_dropout_ratio = hidden_dropout_ratio
        self.layer_norm_eps = layer_norm_eps
        self.gelu_checkpoint = gelu_checkpoint
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.normalize_invertible = normalize_invertible
        self.attn = TransformerLayer(
            hidden_size=hidden_size, heads=heads, causal=causal,
            attn_dropout_ratio=attn_dropout_ratio,
            hidden_dropout_ratio=hidden_dropout_ratio,
            initializer_range=initializer_range,
            layer_norm_eps=layer_norm_eps, attn_impl=attn_impl,
            sparsity_config=sparsity_config)
        self.moe = MoEFFN(hidden_size, intermediate_size or 4 * hidden_size,
                          num_experts, k=k, capacity_factor=capacity_factor,
                          initializer_range=initializer_range)

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        # attention params come from the real TransformerLayer init (minus
        # its dense FFN), so layout changes there propagate here
        attn_full = self.attn.init(k1)
        params = {k: attn_full[k] for k in self._ATTN_PARAM_KEYS}
        params["moe"] = self.moe.init(k2)
        return params

    @classmethod
    def partition_specs(cls):
        attn_full = TransformerLayer.partition_specs()
        specs = {k: attn_full[k] for k in cls._ATTN_PARAM_KEYS}
        specs["moe"] = MoEFFN.partition_specs()
        return specs

    def apply(self, params, x, key_padding_mask=None, rng=None,
              deterministic=True):
        r1 = r2 = r3 = None
        if rng is not None and not deterministic:
            r1, r2, r3 = jax.random.split(rng, 3)

        def attention_block(p, y):
            ctx = self.attn.attention_core(p, y,
                                           key_padding_mask=key_padding_mask,
                                           attn_rng=r1,
                                           deterministic=deterministic)
            out = dense(p["attn_out"], ctx)
            return dropout(r2, out, self.hidden_dropout_ratio, deterministic)

        def moe_block(p, y):
            moe_out, aux = self.moe.apply(p["moe"], y)
            # residual dropout on the FFN path, matching the dense mlp_block
            return dropout(r3, moe_out, self.hidden_dropout_ratio,
                           deterministic), aux

        def ln(p, y):
            return layer_norm(p, y, self.layer_norm_eps)

        # same memory knobs as the dense block (reference kernel flags)
        if self.attn_dropout_checkpoint:
            attention_block = jax.checkpoint(attention_block)
        if self.gelu_checkpoint:
            moe_block = jax.checkpoint(moe_block)
        if self.normalize_invertible:
            ln = jax.checkpoint(ln)

        x = x + attention_block(params, ln(params["ln_attn"], x))
        moe_out, aux = moe_block(params, ln(params["ln_mlp"], x))
        return x + moe_out, aux
