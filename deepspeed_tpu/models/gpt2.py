"""GPT-2 model family (flagship decoder model).

Fills the role of the reference's Megatron-GPT2 integration tests and perf
configs (``tests/model/Megatron_GPT2``; BASELINE configs #3/#4).  Decoder-
only transformer with pre-layernorm blocks (GPT-2 convention), causal flash
attention, weight-tied LM head, optional per-layer remat, and Megatron-style
tensor-parallel partition specs.

Batch contract: ``batch = {"input_ids"[, "labels"]}``; labels default to
shifted input_ids; ``-100`` positions are ignored.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .layers import (TransformerLayer, cross_entropy_with_logits, dropout,
                     embedding_init, layer_norm)


class GPT2Config:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_position_embeddings=1024,
                 embd_dropout=0.1, attn_dropout=0.1, resid_dropout=0.1,
                 initializer_range=0.02, layer_norm_eps=1e-5, remat=False,
                 attn_impl="auto", sparsity_config=None,
                 gelu_checkpoint=False, attn_dropout_checkpoint=False,
                 normalize_invertible=False,
                 moe_experts=0, moe_every=2, moe_k=2,
                 moe_capacity_factor=1.25, moe_aux_coef=0.01,
                 loss_chunk=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_position_embeddings = max_position_embeddings
        self.embd_dropout = embd_dropout
        self.attn_dropout = attn_dropout
        self.resid_dropout = resid_dropout
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.gelu_checkpoint = gelu_checkpoint
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.normalize_invertible = normalize_invertible
        # loss_chunk > 0: fused LM-head + CE over sequence chunks of this
        # size (never materializes the full [b, s, vocab] logits; backward
        # recomputes per chunk).  Loss is exactly the full-logits value.
        self.loss_chunk = loss_chunk
        # MoE (beyond-reference; expert parallelism over the 'expert' axis):
        # moe_experts > 0 swaps the dense FFN for a routed-expert FFN on
        # every moe_every-th block (GShard-style alternation)
        self.moe_experts = moe_experts
        self.moe_every = moe_every
        self.moe_k = moe_k
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_aux_coef = moe_aux_coef
        self.remat = remat
        self.attn_impl = attn_impl
        self.sparsity_config = sparsity_config

    @staticmethod
    def gpt2_small(**kw):
        return GPT2Config(hidden_size=768, num_layers=12, num_heads=12, **kw)

    @staticmethod
    def gpt2_medium(**kw):
        """GPT-2 345M (BASELINE config #3)."""
        return GPT2Config(hidden_size=1024, num_layers=24, num_heads=16, **kw)

    @staticmethod
    def gpt2_large(**kw):
        return GPT2Config(hidden_size=1280, num_layers=36, num_heads=20, **kw)

    @staticmethod
    def gpt2_xl(**kw):
        """GPT-2 1.5B (BASELINE config #4)."""
        return GPT2Config(hidden_size=1600, num_layers=48, num_heads=25, **kw)


class GPT2LMHeadTPU:
    def __init__(self, config: GPT2Config, compute_dtype=None):
        self.config = config
        self.compute_dtype = compute_dtype
        self.layer = TransformerLayer(
            hidden_size=config.hidden_size, heads=config.num_heads,
            causal=True, attn_dropout_ratio=config.attn_dropout,
            hidden_dropout_ratio=config.resid_dropout, pre_layer_norm=True,
            initializer_range=config.initializer_range,
            layer_norm_eps=config.layer_norm_eps,
            attn_impl=config.attn_impl,
            sparsity_config=config.sparsity_config,
            gelu_checkpoint=config.gelu_checkpoint,
            attn_dropout_checkpoint=config.attn_dropout_checkpoint,
            normalize_invertible=config.normalize_invertible)
        self.moe_layer = None
        if config.moe_experts:
            from .moe import MoETransformerLayer

            self.moe_layer = MoETransformerLayer(
                hidden_size=config.hidden_size, heads=config.num_heads,
                num_experts=config.moe_experts, causal=True,
                k=config.moe_k, capacity_factor=config.moe_capacity_factor,
                attn_dropout_ratio=config.attn_dropout,
                hidden_dropout_ratio=config.resid_dropout,
                initializer_range=config.initializer_range,
                layer_norm_eps=config.layer_norm_eps,
                attn_impl=config.attn_impl,
                sparsity_config=config.sparsity_config,
                gelu_checkpoint=config.gelu_checkpoint,
                attn_dropout_checkpoint=config.attn_dropout_checkpoint,
                normalize_invertible=config.normalize_invertible)

    def _is_moe_layer(self, i):
        c = self.config
        return bool(c.moe_experts) and i % c.moe_every == c.moe_every - 1

    def init(self, rng):
        c = self.config
        keys = jax.random.split(rng, c.num_layers + 3)
        return {
            "wte": embedding_init(keys[0], c.vocab_size, c.hidden_size,
                                  c.initializer_range),
            "wpe": embedding_init(keys[1], c.max_position_embeddings,
                                  c.hidden_size, c.initializer_range),
            "blocks": {f"layer_{i}": (self.moe_layer.init(keys[2 + i])
                                      if self._is_moe_layer(i)
                                      else self.layer.init(keys[2 + i]))
                       for i in range(c.num_layers)},
            "ln_f": {"scale": jnp.ones((c.hidden_size,), jnp.float32),
                     "bias": jnp.zeros((c.hidden_size,), jnp.float32)},
        }

    def sparse_gradient_paths(self):
        """Embedding leaves with genuinely row-sparse gradients (the
        reference's nn.Embedding auto-detect, ``engine.py:180-185``).
        ``wte`` does NOT qualify: the LM head ties to it, and the vocab
        projection's backward puts gradient mass on EVERY vocab row, so a
        row-sparse exchange would drop most of it (the engine would poison
        the step with NaN).  ``wpe`` rows are all touched every step, so
        there is nothing to compress either."""
        return ()

    def partition_specs(self, mesh):
        c = self.config
        has_model = "model" in mesh.axis_names
        layer_spec = TransformerLayer.partition_specs()
        moe_spec = None
        if c.moe_experts:
            from .moe import MoETransformerLayer

            moe_spec = MoETransformerLayer.partition_specs()
        return {
            "wte": P("model", None) if has_model else P(),
            "wpe": P(),
            "blocks": {f"layer_{i}": (moe_spec if self._is_moe_layer(i)
                                      else layer_spec)
                       for i in range(c.num_layers)},
            "ln_f": {"scale": P(), "bias": P()},
        }

    def hidden(self, params, input_ids, rng=None, deterministic=True):
        """Trunk + final layernorm → [b, s, h] (pre-LM-head hidden states)."""
        c = self.config
        b, s = input_ids.shape
        x = jnp.take(params["wte"], input_ids, axis=0) + params["wpe"][None, :s]
        if self.compute_dtype is not None:
            x = x.astype(self.compute_dtype)
        if rng is not None and not deterministic:
            rng_e, rng = jax.random.split(rng)
            x = dropout(rng_e, x, c.embd_dropout, deterministic)

        aux_losses = []

        def run_layer(layer_params, x, layer_rng):
            return self.layer.apply(layer_params, x, rng=layer_rng,
                                    deterministic=deterministic)

        def run_moe_layer(layer_params, x, layer_rng):
            return self.moe_layer.apply(layer_params, x, rng=layer_rng,
                                        deterministic=deterministic)

        ck_layer = ck_moe_layer = None
        if c.remat:
            from ..runtime.activation_checkpointing import checkpointing as ds_ckpt

            ck_layer = ds_ckpt.checkpoint_wrapper(run_layer)
            if self.moe_layer is not None:
                ck_moe_layer = ds_ckpt.checkpoint_wrapper(run_moe_layer)

        for i in range(c.num_layers):
            layer_rng = None
            if rng is not None and not deterministic:
                rng, layer_rng = jax.random.split(rng)
            if self._is_moe_layer(i):
                fn = run_moe_layer
                if ck_moe_layer is not None:
                    from ..runtime.activation_checkpointing import checkpointing as ds_ckpt

                    if ds_ckpt.should_checkpoint_layer(i, c.num_layers):
                        fn = ck_moe_layer
                with jax.named_scope(f"layer_{i}_moe"):
                    x, aux = fn(params["blocks"][f"layer_{i}"], x, layer_rng)
                    aux_losses.append(aux)
                continue
            fn = run_layer
            if ck_layer is not None:
                from ..runtime.activation_checkpointing import checkpointing as ds_ckpt

                if ds_ckpt.should_checkpoint_layer(i, c.num_layers):
                    fn = ck_layer
            with jax.named_scope(f"layer_{i}"):
                x = fn(params["blocks"][f"layer_{i}"], x, layer_rng)

        x = layer_norm(params["ln_f"], x, c.layer_norm_eps)
        self._last_moe_aux = (sum(aux_losses) / len(aux_losses)
                              if aux_losses else None)
        return x

    @staticmethod
    def _lm_head(params, x):
        """Tied LM head (wte shared with the input embedding; the
        reference ties them through TiedLayerSpec under pipelining)."""
        return x @ params["wte"].T.astype(x.dtype)

    def logits(self, params, input_ids, rng=None, deterministic=True):
        x = self.hidden(params, input_ids, rng=rng, deterministic=deterministic)
        return self._lm_head(params, x)

    def _chunked_lm_loss(self, params, x, labels, chunk):
        """Fused LM-head + cross entropy over sequence chunks.

        The full-logits path materializes [b, s, V] (824 MB bf16 at
        GPT-2-medium bench shape) and upcasts it to fp32 for the
        logsumexp (3.3 GB) — the single biggest tensor in the step.  Here
        each chunk's logits [b, chunk, V] live only inside one
        ``lax.map`` iteration and the backward recomputes them
        (``jax.checkpoint``), the reference's fused-kernel philosophy
        (``csrc/transformer/gelu_kernels.cu``-class fusion) applied to
        the head: HBM high-water drops by ~the logits tensor.
        """
        b, s, h = x.shape
        n = s // chunk
        assert s % chunk == 0, f"seq {s} not divisible by loss_chunk {chunk}"
        w = params["wte"]
        xs = x.reshape(b, n, chunk, h).swapaxes(0, 1)        # [n,b,chunk,h]
        ls = labels.reshape(b, n, chunk).swapaxes(0, 1)

        @jax.checkpoint
        def one(args):
            xc, lc = args
            logits = (xc @ w.T.astype(xc.dtype)).astype(jnp.float32)
            mask = lc != -100
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, jnp.where(mask, lc, 0)[..., None], axis=-1)[..., 0]
            return jnp.sum((lse - gold) * mask), jnp.sum(mask)

        sums, counts = jax.lax.map(one, (xs, ls))
        return jnp.sum(sums) / jnp.maximum(jnp.sum(counts), 1)

    def apply(self, params, batch, rng=None, train=True, **kw):
        c = self.config
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        want_logits = not train and not (isinstance(batch, dict)
                                         and "labels" in batch)
        chunk = getattr(c, "loss_chunk", 0)
        use_chunked = (not want_logits and chunk
                       and input_ids.shape[1] % chunk == 0)
        if chunk and not want_logits and not use_chunked:
            from ..utils.logging import logger

            logger.warning(
                "loss_chunk=%s does not divide seq %s — falling back to the "
                "FULL-logits loss (the [b, s, vocab] tensor this knob exists "
                "to avoid WILL be materialized); pick a divisor",
                chunk, input_ids.shape[1])
        x = self.hidden(params, input_ids, rng=rng, deterministic=not train)
        if want_logits:
            return self._lm_head(params, x)
        if isinstance(batch, dict) and "labels" in batch:
            labels = batch["labels"]
        else:
            labels = jnp.concatenate(
                [input_ids[:, 1:],
                 jnp.full((input_ids.shape[0], 1), -100, input_ids.dtype)], axis=1)
        if use_chunked:
            loss = self._chunked_lm_loss(params, x, labels, int(chunk))
        else:
            loss = cross_entropy_with_logits(self._lm_head(params, x), labels,
                                             ignore_index=-100)
        if train and getattr(self, "_last_moe_aux", None) is not None:
            # Switch load-balancing aux loss (training-only regularizer),
            # averaged over MoE blocks; eval loss stays comparable to dense
            loss = loss + self.config.moe_aux_coef * self._last_moe_aux
        return loss
