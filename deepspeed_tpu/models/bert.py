"""BERT model family (flagship encoder model).

Fills the role of the reference's BERT usage: the DeepSpeedExamples
``bing_bert`` pretraining flow and the fused-kernel test models
(``tests/unit/modeling.py``, ``modelingpreln.py``).  Implemented TPU-first:
one fused QKV GEMM per layer, flash attention, bf16-friendly fp32
layernorms, optional pre-layernorm (the reference's ``pre_layer_norm``
kernel knob), ``jax.checkpoint`` rematerialization per layer (the
reference's activation checkpointing, SURVEY §5.7), and Progressive Layer
Drop support (``pld_theta`` kwarg; reference
``runtime/progressive_layer_drop.py``).

Batch contract for pretraining (``BertForPreTrainingTPU``):
``batch = {"input_ids", "attention_mask", "token_type_ids", "masked_lm_labels",
"next_sentence_labels"}`` → scalar loss (MLM + NSP), mirroring the bing_bert
batch layout.
"""


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .layers import (TransformerLayer, cross_entropy_with_logits, dense,
                     dropout, embedding_init, gelu, layer_norm, _dense_init)


class BertConfig:
    def __init__(self, vocab_size=30528, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=512, type_vocab_size=2,
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 initializer_range=0.02, pre_layer_norm=False,
                 layer_norm_eps=1e-12, remat=False,
                 attn_impl="auto", sparsity_config=None,
                 gelu_checkpoint=False, attn_dropout_checkpoint=False,
                 normalize_invertible=False, max_predictions_per_seq=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.pre_layer_norm = pre_layer_norm
        self.layer_norm_eps = layer_norm_eps
        self.remat = remat
        self.attn_impl = attn_impl
        self.sparsity_config = sparsity_config
        # kernel memory knobs (reference DeepSpeedTransformerConfig,
        # ops/transformer/transformer.py:109-137)
        self.gelu_checkpoint = gelu_checkpoint
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.normalize_invertible = normalize_invertible
        # MLM head masked-position gather: when set, the transform + vocab
        # projection run over only this many gathered positions per row
        # instead of all of them (~15% of positions carry labels — the
        # projection over the other 85% is wasted FLOPs, ~8% of the step at
        # seq 128).  Must be >= the per-row masked count the data pipeline
        # produces (bing_bert's max_predictions_per_seq contract); rows
        # with more labels than this have the excess silently ignored.
        self.max_predictions_per_seq = max_predictions_per_seq

    @staticmethod
    def bert_base(**kw):
        return BertConfig(hidden_size=768, num_hidden_layers=12,
                          num_attention_heads=12, **kw)

    @staticmethod
    def bert_large(**kw):
        return BertConfig(hidden_size=1024, num_hidden_layers=24,
                          num_attention_heads=16, **kw)


class BertModel:
    """Encoder trunk: embeddings + N transformer layers (+pooler)."""

    def __init__(self, config: BertConfig):
        self.config = config
        self.layer = TransformerLayer(
            hidden_size=config.hidden_size, heads=config.num_attention_heads,
            intermediate_size=config.intermediate_size, causal=False,
            attn_dropout_ratio=config.attention_probs_dropout_prob,
            hidden_dropout_ratio=config.hidden_dropout_prob,
            pre_layer_norm=config.pre_layer_norm,
            initializer_range=config.initializer_range,
            layer_norm_eps=config.layer_norm_eps,
            attn_impl=config.attn_impl,
            sparsity_config=config.sparsity_config,
            gelu_checkpoint=config.gelu_checkpoint,
            attn_dropout_checkpoint=config.attn_dropout_checkpoint,
            normalize_invertible=config.normalize_invertible)

    def init(self, rng):
        c = self.config
        keys = jax.random.split(rng, c.num_hidden_layers + 5)
        params = {
            "embeddings": {
                "word": embedding_init(keys[0], c.vocab_size, c.hidden_size,
                                       c.initializer_range),
                "position": embedding_init(keys[1], c.max_position_embeddings,
                                           c.hidden_size, c.initializer_range),
                "token_type": embedding_init(keys[2], c.type_vocab_size,
                                             c.hidden_size, c.initializer_range),
                "ln": {"scale": jnp.ones((c.hidden_size,), jnp.float32),
                       "bias": jnp.zeros((c.hidden_size,), jnp.float32)},
            },
            "encoder": {f"layer_{i}": self.layer.init(keys[3 + i])
                        for i in range(c.num_hidden_layers)},
            "pooler": _dense_init(keys[-2], c.hidden_size, c.hidden_size,
                                  c.initializer_range),
        }
        return params

    def partition_specs(self, mesh):
        c = self.config
        layer_spec = TransformerLayer.partition_specs()
        emb = P("model", None) if "model" in mesh.axis_names else P()
        return {
            "embeddings": {"word": emb, "position": P(), "token_type": P(),
                           "ln": {"scale": P(), "bias": P()}},
            "encoder": {f"layer_{i}": layer_spec for i in range(c.num_hidden_layers)},
            "pooler": {"kernel": P(), "bias": P()},
        }

    def encode(self, params, input_ids, attention_mask=None, token_type_ids=None,
               rng=None, deterministic=True, pld_theta=None, dtype=None,
               final_positions=None):
        """``final_positions`` [b, K]: compute the LAST encoder layer only
        at these positions (queries gathered, K/V full — see
        ``TransformerLayer.apply``); the returned sequence output is
        [b, K, hidden] and the pooler reads row 0, so callers must put
        position 0 first.  Ignored under Progressive Layer Drop (the
        keep/passthrough select needs uniform shapes)."""
        c = self.config
        b, s = input_ids.shape
        emb = params["embeddings"]
        x = (jnp.take(emb["word"], input_ids, axis=0)
             + emb["position"][None, :s]
             + (jnp.take(emb["token_type"], token_type_ids, axis=0)
                if token_type_ids is not None else 0.0))
        if dtype is not None:
            x = x.astype(dtype)
        x = layer_norm(emb["ln"], x, c.layer_norm_eps)
        if rng is not None and not deterministic:
            rng_e, rng = jax.random.split(rng)
            x = dropout(rng_e, x, c.hidden_dropout_prob, deterministic)

        # Key-padding form (1 = visible), so the flash kernel can fuse the
        # mask into its softmax instead of falling back to O(s²) attention.
        kpm = attention_mask

        def run_layer(layer_params, x, layer_rng):
            return self.layer.apply(layer_params, x, key_padding_mask=kpm,
                                    rng=layer_rng, deterministic=deterministic)

        ck_layer = None
        if c.remat:
            from ..runtime.activation_checkpointing import checkpointing as ds_ckpt

            ck_layer = ds_ckpt.checkpoint_wrapper(run_layer)

        if pld_theta is not None:
            final_positions = None  # PLD's select needs uniform shapes

        def run_last_layer(layer_params, x, layer_rng):
            return self.layer.apply(layer_params, x, key_padding_mask=kpm,
                                    rng=layer_rng, deterministic=deterministic,
                                    positions=final_positions)

        for i in range(c.num_hidden_layers):
            layer_rng = None
            if rng is not None and not deterministic:
                rng, layer_rng = jax.random.split(rng)
            last = (i == c.num_hidden_layers - 1)
            fn = run_last_layer if (last and final_positions is not None) \
                else run_layer
            if ck_layer is not None:
                from ..runtime.activation_checkpointing import checkpointing as ds_ckpt

                if ds_ckpt.should_checkpoint_layer(i, c.num_hidden_layers):
                    fn = (ds_ckpt.checkpoint_wrapper(run_last_layer)
                          if (last and final_positions is not None)
                          else ck_layer)
            with jax.named_scope(f"layer_{i}"):
                y = fn(params["encoder"][f"layer_{i}"], x, layer_rng)
            if pld_theta is not None and not deterministic and layer_rng is not None:
                # Progressive Layer Drop: keep layer with prob θ; residual
                # pass-through otherwise (reference PLD wiring
                # engine.py:809-810 + bing_bert modeling).  Expressed as a
                # select so the program stays static-shape for XLA.
                keep = jax.random.bernoulli(jax.random.fold_in(layer_rng, 17),
                                            jnp.clip(pld_theta, 0.0, 1.0))
                x = jnp.where(keep, y, x)
            else:
                x = y
        pooled = jnp.tanh(dense(params["pooler"], x[:, 0]))
        return x, pooled


class BertForPreTrainingTPU:
    """MLM + NSP pretraining objective (bing_bert parity)."""

    def __init__(self, config: BertConfig, compute_dtype=None):
        self.config = config
        self.bert = BertModel(config)
        self.compute_dtype = compute_dtype

    def init(self, rng):
        c = self.config
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        params = {"bert": self.bert.init(k1)}
        params["cls"] = {
            "transform": _dense_init(k2, c.hidden_size, c.hidden_size,
                                     c.initializer_range),
            "transform_ln": {"scale": jnp.ones((c.hidden_size,), jnp.float32),
                             "bias": jnp.zeros((c.hidden_size,), jnp.float32)},
            "decoder_bias": jnp.zeros((c.vocab_size,), jnp.float32),
            "seq_relationship": _dense_init(k3, c.hidden_size, 2,
                                            c.initializer_range),
        }
        return params

    def sparse_gradient_paths(self):
        """Embedding leaves with genuinely row-sparse gradients (the
        reference's nn.Embedding auto-detect, ``engine.py:180-185``).  The
        word embedding does NOT qualify here: the MLM decoder ties to it
        (``apply``), and the vocab projection's backward puts gradient on
        EVERY vocab row — a row-sparse exchange would drop most of it (the
        engine poisons such a step with NaN rather than train silently
        wrong).  The 2-row token_type table can never beat its own exchange
        overhead either, so the pretraining model declares NOTHING — the
        engine then keeps the plain GSPMD path.  The untied heads (QA,
        classification) do declare the word embedding."""
        return ()

    def partition_specs(self, mesh):
        has_model = "model" in mesh.axis_names
        return {
            "bert": self.bert.partition_specs(mesh),
            "cls": {
                "transform": {"kernel": P(), "bias": P()},
                "transform_ln": {"scale": P(), "bias": P()},
                "decoder_bias": P("model") if has_model else P(),
                "seq_relationship": {"kernel": P(), "bias": P()},
            },
        }

    def apply(self, params, batch, rng=None, train=True, pld_theta=None, **kw):
        c = self.config
        input_ids = batch["input_ids"]
        attention_mask = batch.get("attention_mask")
        token_type_ids = batch.get("token_type_ids")
        mlm_labels = batch.get("masked_lm_labels")
        n_pred = c.max_predictions_per_seq
        # Gather the labeled positions before the head — and, when PLD is
        # off, before the FINAL encoder layer too (its outputs at other
        # positions feed nothing): only ~15% of positions carry MLM
        # labels, so the last layer + vocab projection over the rest is
        # pure waste (the reference pays it; this is the fused-kernel
        # philosophy applied at the model level).  top_k of the label mask
        # is stable, so it selects the FIRST n_pred labeled positions;
        # unlabeled fill positions gather a -100 label and are ignored by
        # the loss.  Position 0 rides along for the pooler/NSP head.
        gather = (mlm_labels is not None and n_pred
                  and n_pred < input_ids.shape[1])
        final_positions = None
        if gather:
            is_masked = (mlm_labels != -100).astype(jnp.int32)
            _, pos = jax.lax.top_k(is_masked, n_pred)  # [b, n_pred]
            mlm_labels = jnp.take_along_axis(mlm_labels, pos, axis=1)
            # final-layer query gather needs the dense bidirectional
            # attention core and uniform shapes (no PLD select); other
            # configs keep the full final layer + post-encode head gather
            if pld_theta is None and c.attn_impl == "auto":
                final_positions = jnp.concatenate(
                    [jnp.zeros((pos.shape[0], 1), pos.dtype), pos], axis=1)
        seq_out, pooled = self.bert.encode(
            params["bert"], input_ids, attention_mask, token_type_ids,
            rng=rng, deterministic=not train, pld_theta=pld_theta,
            dtype=self.compute_dtype, final_positions=final_positions)

        cls = params["cls"]
        head_in = seq_out
        if gather:
            if final_positions is not None:
                # encode returned [b, 1 + n_pred, h]: CLS row + label rows
                head_in = seq_out[:, 1:]
            else:  # PLD active — encode ran full-length; gather here
                head_in = jnp.take_along_axis(seq_out, pos[..., None], axis=1)
        h = gelu(dense(cls["transform"], head_in))
        h = layer_norm(cls["transform_ln"], h, c.layer_norm_eps)
        # decoder tied to word embeddings (standard BERT; the reference ties
        # them through TiedLayerSpec under pipelining, module.py:71)
        logits = h @ params["bert"]["embeddings"]["word"].T.astype(h.dtype) \
            + cls["decoder_bias"].astype(h.dtype)

        if not train and mlm_labels is None:
            return logits

        mlm_loss = cross_entropy_with_logits(logits, mlm_labels,
                                             ignore_index=-100)
        loss = mlm_loss
        if "next_sentence_labels" in batch:
            nsp_logits = dense(cls["seq_relationship"], pooled)
            nsp_loss = cross_entropy_with_logits(nsp_logits,
                                                 batch["next_sentence_labels"])
            loss = loss + nsp_loss
        return loss


class BertForQuestionAnsweringTPU:
    """Extractive QA (SQuAD) head: per-token start/end logits.

    Parity target: the reference's BingBertSquad fine-tuning flow
    (``tests/model/BingBertSquad/test_e2e_squad.py``) whose model is BERT +
    a 2-output span classifier.  Batch: ``{"input_ids", "attention_mask",
    "token_type_ids", "start_positions", "end_positions"}`` → scalar loss;
    without positions, returns ``(start_logits, end_logits)``.
    """

    def __init__(self, config: BertConfig, compute_dtype=None):
        self.config = config
        self.bert = BertModel(config)
        self.compute_dtype = compute_dtype

    def sparse_gradient_paths(self):
        # no tied LM head here, so the word embedding's grad really is
        # row-sparse (only token rows touched)
        return ("bert/embeddings/word", "bert/embeddings/token_type")

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"bert": self.bert.init(k1),
                "qa_outputs": _dense_init(k2, self.config.hidden_size, 2,
                                          self.config.initializer_range)}

    def partition_specs(self, mesh):
        return {"bert": self.bert.partition_specs(mesh),
                "qa_outputs": {"kernel": P(), "bias": P()}}

    def apply(self, params, batch, rng=None, train=True, **kw):
        seq_out, _ = self.bert.encode(
            params["bert"], batch["input_ids"], batch.get("attention_mask"),
            batch.get("token_type_ids"), rng=rng, deterministic=not train,
            dtype=self.compute_dtype)
        logits = dense(params["qa_outputs"], seq_out)  # [b, s, 2]
        start_logits = logits[..., 0]
        end_logits = logits[..., 1]
        if "start_positions" not in batch and "end_positions" not in batch:
            return start_logits, end_logits
        assert "start_positions" in batch and "end_positions" in batch, (
            "QA batches must carry both start_positions and end_positions")
        # out-of-range positions (truncated/unanswerable spans in SQuAD
        # preprocessing) contribute nothing — torch CrossEntropyLoss
        # ignored_index semantics, via this codebase's ignore_index path
        s_len = start_logits.shape[1]

        def ignore_oob(pos):
            return jnp.where((pos < 0) | (pos >= s_len), -100, pos)

        loss = 0.5 * (
            cross_entropy_with_logits(start_logits,
                                      ignore_oob(batch["start_positions"]))
            + cross_entropy_with_logits(end_logits,
                                        ignore_oob(batch["end_positions"])))
        return loss


class BertForSequenceClassificationTPU:
    """[CLS]-pooled classification/regression head (GLUE-style).

    Batch: ``{"input_ids", "attention_mask", "token_type_ids", "labels"}``
    → scalar loss; without labels, returns [b, num_labels] logits.
    Integer labels → cross entropy; float labels → mean-squared error on
    the squeezed logits (STS-B-style regression).
    """

    def __init__(self, config: BertConfig, num_labels=2, compute_dtype=None):
        self.config = config
        self.num_labels = num_labels
        self.bert = BertModel(config)
        self.compute_dtype = compute_dtype

    def sparse_gradient_paths(self):
        # untied trunk (see BertForQuestionAnsweringTPU)
        return ("bert/embeddings/word", "bert/embeddings/token_type")

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"bert": self.bert.init(k1),
                "classifier": _dense_init(k2, self.config.hidden_size,
                                          self.num_labels,
                                          self.config.initializer_range)}

    def partition_specs(self, mesh):
        return {"bert": self.bert.partition_specs(mesh),
                "classifier": {"kernel": P(), "bias": P()}}

    def apply(self, params, batch, rng=None, train=True, **kw):
        _, pooled = self.bert.encode(
            params["bert"], batch["input_ids"], batch.get("attention_mask"),
            batch.get("token_type_ids"), rng=rng, deterministic=not train,
            dtype=self.compute_dtype)
        if rng is not None and train:
            pooled = dropout(jax.random.fold_in(rng, 99), pooled,
                             self.config.hidden_dropout_prob, False)
        logits = dense(params["classifier"], pooled)
        if "labels" not in batch:
            return logits
        labels = batch["labels"]
        if jnp.issubdtype(jnp.asarray(labels).dtype, jnp.floating):
            preds = jnp.squeeze(logits, -1) if logits.shape[-1] == 1 else logits
            return jnp.mean((preds.astype(jnp.float32)
                             - labels.astype(jnp.float32)) ** 2)
        return cross_entropy_with_logits(logits, labels)
