"""Transformer building blocks (pure-JAX, MXU-first).

These are the framework's reference transformer layers — the role the fused
CUDA ``DeepSpeedTransformerLayer`` plays in the reference
(``deepspeed/ops/transformer/transformer.py:470``; kernels
``csrc/transformer/ds_transformer_cuda.cpp:145-1040``).  Design notes:

- Weights are plain pytrees; layouts keep matmuls large and bf16-friendly
  (QKV fused into one ``(hidden, 3·hidden)`` GEMM like the reference's qkv
  concat, ``module_inject/replace_module.py``).
- Tensor parallelism is declared, not coded: ``partition_specs`` returns
  Megatron-style PartitionSpecs (column-parallel QKV/FC1, row-parallel
  out/FC2) and XLA GSPMD inserts the all-reduces.
- Attention dispatches to the fused Pallas flash-attention kernel on TPU
  (``ops/transformer/attention.py``) and falls back to a jnp reference
  implementation elsewhere.
- ``pre_layer_norm``, dropout sites, and activation-checkpoint knobs mirror
  the reference config (``DeepSpeedTransformerConfig``,
  ``ops/transformer/transformer.py:39-154``).
"""

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.op_common import random_keep
from ..ops.transformer.attention import (dot_product_attention,
                                         key_padding_to_additive)


def _dense_init(rng, in_dim, out_dim, initializer_range=0.02):
    return {
        "kernel": jax.random.normal(rng, (in_dim, out_dim), jnp.float32)
        * initializer_range,
        "bias": jnp.zeros((out_dim,), jnp.float32),
    }


def dense(params, x):
    return x @ params["kernel"].astype(x.dtype) + params["bias"].astype(x.dtype)


def layer_norm(params, x, eps=1e-12):
    """LayerNorm in fp32 accumulations (bf16-safe), fused by XLA."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * params["scale"] + params["bias"]).astype(x.dtype)


def gelu(x):
    # tanh approximation: matches the reference kernel (gelu_kernels.cu) and
    # keeps everything elementwise-fusable.
    x32 = x.astype(jnp.float32)
    y = 0.5 * x32 * (1.0 + jnp.tanh(0.7978845608028654 * (x32 + 0.044715 * x32 ** 3)))
    return y.astype(x.dtype)


def dropout(rng, x, rate, deterministic):
    if deterministic or rate < 1.0 / 512.0 or rng is None:
        return x
    keep, scale = random_keep(rng, x.shape, rate)
    return jnp.where(keep, x * jnp.asarray(scale, x.dtype), jnp.zeros_like(x))


class TransformerLayer:
    """One encoder/decoder layer.

    Config mirrors ``DeepSpeedTransformerConfig`` (reference
    ``ops/transformer/transformer.py:39-154``): ``pre_layer_norm``,
    ``attn_dropout_ratio``, ``hidden_dropout_ratio``, ``initializer_range``.
    ``causal`` turns it into a GPT block.
    """

    def __init__(self, hidden_size, heads, intermediate_size=None, causal=False,
                 attn_dropout_ratio=0.1, hidden_dropout_ratio=0.1,
                 pre_layer_norm=False, initializer_range=0.02, layer_norm_eps=1e-12,
                 attn_impl="auto", sparsity_config=None,
                 gelu_checkpoint=False, attn_dropout_checkpoint=False,
                 normalize_invertible=False, stochastic_mode=False):
        assert hidden_size % heads == 0
        self.hidden_size = hidden_size
        self.heads = heads
        self.head_dim = hidden_size // heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.causal = causal
        self.attn_dropout_ratio = attn_dropout_ratio
        self.hidden_dropout_ratio = hidden_dropout_ratio
        self.pre_layer_norm = pre_layer_norm
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        # memory knobs mirroring DeepSpeedTransformerConfig (reference
        # ops/transformer/transformer.py:109-137): each drops a class of
        # saved activations and recomputes it in backward — here expressed
        # as jax.checkpoint around the corresponding sub-block (the
        # reference frees the buffer and replays the kernel)
        self.gelu_checkpoint = gelu_checkpoint
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.normalize_invertible = normalize_invertible
        # Reference knob parity: stochastic_mode trades run-to-run
        # determinism for ~2% speed via non-deterministic CUDA atomics
        # (ops/transformer/transformer.py:93-107,
        # op_builder/stochastic_transformer.py).  XLA:TPU execution is
        # deterministic by construction — there is no atomics-ordering
        # speed to buy back — so the knob is accepted for config
        # compatibility and logged as a no-op.
        self.stochastic_mode = stochastic_mode
        if stochastic_mode:
            from ..utils.logging import logger

            logger.warning(
                "stochastic_mode=True accepted for reference config parity "
                "but is a no-op on TPU: XLA execution is deterministic and "
                "there is no non-deterministic-atomics fast path to enable")
        # attention core selection:
        #   'auto'   — flash kernel on TPU / jnp reference elsewhere
        #   'ring'   — sequence-parallel ring attention over the 'seq' mesh
        #              axis (long-context; SURVEY §5.7 upgrade)
        #   'sparse' — block-sparse attention driven by sparsity_config
        #              (reference ops/sparse_attention)
        assert attn_impl in ("auto", "ring", "sparse")
        self.attn_impl = attn_impl
        self.sparsity_config = sparsity_config
        self._layout_cache = {}  # seq_len -> layout (stable across traces)
        if attn_impl == "sparse":
            assert sparsity_config is not None, (
                "attn_impl='sparse' requires a SparsityConfig")

    def _sparse_layout(self, seq_len):
        """Layout cached per sequence length: randomized configs (BigBird,
        Variable) must yield the SAME pattern in every traced program
        (train/eval/retrace), not a fresh sample per trace."""
        if seq_len not in self._layout_cache:
            self._layout_cache[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[seq_len]

    def init(self, rng) -> Dict[str, Any]:
        ks = jax.random.split(rng, 4)
        h, i = self.hidden_size, self.intermediate_size
        return {
            "qkv": _dense_init(ks[0], h, 3 * h, self.initializer_range),
            "attn_out": _dense_init(ks[1], h, h, self.initializer_range),
            "fc1": _dense_init(ks[2], h, i, self.initializer_range),
            "fc2": _dense_init(ks[3], i, h, self.initializer_range),
            "ln_attn": {"scale": jnp.ones((h,), jnp.float32),
                        "bias": jnp.zeros((h,), jnp.float32)},
            "ln_mlp": {"scale": jnp.ones((h,), jnp.float32),
                       "bias": jnp.zeros((h,), jnp.float32)},
        }

    @staticmethod
    def partition_specs() -> Dict[str, Any]:
        """Megatron TP layout over the ``model`` axis: QKV/FC1 column-
        parallel, out/FC2 row-parallel (SURVEY §2.3 'slice' groups)."""
        col = {"kernel": P(None, "model"), "bias": P("model")}
        row = {"kernel": P("model", None), "bias": P()}
        ln = {"scale": P(), "bias": P()}
        return {"qkv": col, "attn_out": row, "fc1": col, "fc2": row,
                "ln_attn": ln, "ln_mlp": ln}

    def attention_core(self, params, y, mask=None, key_padding_mask=None,
                       attn_rng=None, deterministic=True, positions=None):
        """Fused-QKV attention → [b, s, h] context, honoring the configured
        ``attn_impl`` (auto/ring/sparse) and attention dropout.  Shared by
        the dense block and :class:`~deepspeed_tpu.models.moe.MoETransformerLayer`,
        so every attention variant behaves identically in both.

        ``positions`` [b, K]: compute QUERIES (and hence output rows) only
        at these positions while keys/values cover the full sequence — the
        final-layer optimization for heads that consume a few positions
        (MLM gather).  Identical math for the computed rows."""
        b, s, h = y.shape
        r1 = attn_rng
        if positions is not None:
            assert self.attn_impl == "auto" and not self.causal, (
                "query-gathered attention supports the dense bidirectional "
                "core only")
            K = positions.shape[1]
            w = params["qkv"]["kernel"].astype(y.dtype)
            bias = params["qkv"]["bias"].astype(y.dtype)
            y_sel = jnp.take_along_axis(y, positions[..., None], axis=1)
            q = (y_sel @ w[:, :h] + bias[:h]).reshape(b, K, self.heads,
                                                      self.head_dim)
            kv = (y @ w[:, h:] + bias[h:]).reshape(b, s, 2, self.heads,
                                                   self.head_dim)
            ctx = dot_product_attention(
                q, kv[:, :, 0], kv[:, :, 1], mask=mask,
                key_padding_mask=key_padding_mask,
                causal=False, dropout_rate=self.attn_dropout_ratio,
                dropout_rng=r1, deterministic=deterministic)
            return ctx.reshape(b, K, h)
        qkv = dense(params["qkv"], y)  # [b, s, 3h] one fused GEMM
        qkv = qkv.reshape(b, s, 3, self.heads, self.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        kpm_add = None  # additive [b, s] form for ring/sparse cores
        if self.attn_impl in ("ring", "sparse"):
            if key_padding_mask is not None:
                kpm_add = key_padding_to_additive(key_padding_mask)
            elif mask is not None:
                # the general additive [b, 1, 1, s] broadcast collapses
                assert mask.size == b * s, (
                    f"attn_impl={self.attn_impl!r} supports key-padding "
                    f"masks ([b,1,1,s]), got mask shape {mask.shape}")
                kpm_add = mask.reshape(b, s)
        if self.attn_impl == "ring":
            from ..ops.transformer.ring_attention import ring_attention

            ctx = ring_attention(q, k, v, causal=self.causal,
                                 key_padding_mask=kpm_add)
        elif self.attn_impl == "sparse":
            layout = self._sparse_layout(s)
            causal_sp = self.causal or getattr(
                self.sparsity_config, "attention",
                "bidirectional") == "unidirectional"
            # Pallas LUT-driven kernel on TPU when the layout blocks are
            # MXU-shaped and no key-padding mask is needed; the gather
            # implementation stays as the general/CPU path.
            # DS_SPARSE_FLASH=never forces the gather path.  Read at TRACE
            # time (like DS_FLASH_ATTENTION, ops/transformer/attention.py):
            # set it before the first jitted call — flipping it afterwards
            # has no effect on already-compiled programs (jit cache).
            blk = s // layout.shape[1]
            use_kernel = (kpm_add is None
                          and jax.default_backend() == "tpu"
                          and blk % 128 == 0 and q.shape[-1] % 64 == 0
                          and os.environ.get("DS_SPARSE_FLASH",
                                             "auto") != "never")
            if use_kernel:
                from ..ops.sparse_attention.flash_block_sparse import (
                    flash_block_sparse_attention)

                ctx = flash_block_sparse_attention(q, k, v, layout,
                                                   causal=causal_sp)
            else:
                from ..ops.sparse_attention import block_sparse_attention

                ctx = block_sparse_attention(
                    q, k, v, layout, causal=causal_sp,
                    key_padding_mask=kpm_add, attn_mask=None)
        else:
            ctx = dot_product_attention(
                q, k, v, mask=mask, key_padding_mask=key_padding_mask,
                causal=self.causal,
                dropout_rate=self.attn_dropout_ratio, dropout_rng=r1,
                deterministic=deterministic)
        if self.attn_impl in ("ring", "sparse") and r1 is not None \
                and self.attn_dropout_ratio > 0.0:
            # ring/sparse cores have no in-core dropout; apply it to the
            # attention output so attn_dropout_ratio is honored rather
            # than silently ignored.
            ctx = dropout(r1, ctx, self.attn_dropout_ratio, deterministic)
        return ctx.reshape(b, s, h)

    def apply(self, params, x, mask=None, key_padding_mask=None, rng=None,
              deterministic=True, positions=None):
        """x: [batch, seq, hidden]; mask: [batch, 1, 1, seq] additive or None;
        key_padding_mask: [batch, seq] with 1 at visible tokens (routed to the
        fused flash kernel's mask operand on TPU).

        ``positions`` [b, K]: produce outputs only at these positions
        (attention queries gathered; K/V over the full sequence; FFN and
        layernorms on the K gathered rows).  For the FINAL layer of models
        whose heads consume few positions — identical math for those rows,
        ~(s−K)/s of the layer's FLOPs saved.  Returns [b, K, hidden]."""
        b, s, h = x.shape
        assert mask is None or key_padding_mask is None, (
            "pass either an additive mask or a key_padding_mask, not both")
        r1 = r2 = r3 = None
        if rng is not None and not deterministic:
            r1, r2, r3 = jax.random.split(rng, 3)

        @jax.named_scope("attention")
        def attention_block(params, y):
            ctx = self.attention_core(params, y, mask=mask,
                                      key_padding_mask=key_padding_mask,
                                      attn_rng=r1, deterministic=deterministic,
                                      positions=positions)
            out = dense(params["attn_out"], ctx)
            return dropout(r2, out, self.hidden_dropout_ratio, deterministic)

        @jax.named_scope("mlp")
        def mlp_block(params, y):
            z = gelu(dense(params["fc1"], y))
            z = dense(params["fc2"], z)
            return dropout(r3, z, self.hidden_dropout_ratio, deterministic)

        if self.attn_dropout_checkpoint:
            # don't save attention internals (probs/dropout mask);
            # recompute in backward (reference attn_dropout_checkpoint)
            attention_block = jax.checkpoint(attention_block)
        if self.gelu_checkpoint:
            # recompute gelu/fc1 intermediates (reference gelu_checkpoint)
            mlp_block = jax.checkpoint(mlp_block)

        def ln(p, y):
            return layer_norm(p, y, self.layer_norm_eps)

        if self.normalize_invertible:
            # don't save layernorm inputs (reference normalize_invertible
            # re-derives them; recompute is the XLA-friendly equivalent)
            ln = jax.checkpoint(ln)

        if positions is not None:
            # residuals use the gathered input rows; attention_block already
            # returns [b, K, h]
            def sel(t):
                return jnp.take_along_axis(t, positions[..., None], axis=1)
        else:
            sel = lambda t: t

        if self.pre_layer_norm:
            x = sel(x) + attention_block(params, ln(params["ln_attn"], x))
            x = x + mlp_block(params, ln(params["ln_mlp"], x))
        else:
            x = ln(params["ln_attn"], sel(x) + attention_block(params, x))
            x = ln(params["ln_mlp"], x + mlp_block(params, x))
        return x


def embedding_init(rng, vocab_size, hidden, initializer_range=0.02):
    return jax.random.normal(rng, (vocab_size, hidden), jnp.float32) * initializer_range


def cross_entropy_with_logits(logits, labels, ignore_index=-100):
    """Mean token cross entropy with masking; fp32 logsumexp for stability.

    ``labels == ignore_index`` positions contribute nothing (the reference
    relies on torch's CrossEntropyLoss ignore_index semantics).
    """
    logits = logits.astype(jnp.float32)
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (lse - gold) * mask
    denom = jnp.maximum(jnp.sum(mask), 1)
    return jnp.sum(nll) / denom
