"""Retrace-hazard rules.

``jax.jit`` caches compiled programs keyed on (treedef, shapes, dtypes,
static-arg *values*).  Anything that perturbs that key — or that the
trace captures by Python reference and silently freezes — either
recompiles a minutes-long program mid-training or trains on stale
state.  These rules flag the statically-detectable shapes of that bug.
"""

import ast
from typing import List

from .analysis import ModuleIndex, body_nodes
from .core import (ParsedFile, Rule, call_name, diag, dotted_name,
                   register_file_checker, register_rule)

register_rule(Rule(
    id="DSR301", name="retrace-mutable-default", severity="warning",
    summary="dict/list/set default argument on a jitted callable",
    rationale="A mutable default is one shared object across calls: "
              "mutating it changes traced behavior without retriggering "
              "a trace, and passing it as a static arg fails hashing.",
    autofix_hint="Default to None and construct inside, or use a tuple / "
                 "frozen structure."))

register_rule(Rule(
    id="DSR302", name="retrace-static-unhashable", severity="error",
    summary="static_argnums/static_argnames names a missing or "
            "non-hashable parameter",
    rationale="Static args are hashed into the jit cache key: a "
              "list/dict static arg raises TypeError at call time, and "
              "an out-of-range index marks the wrong parameter static — "
              "retracing on every distinct value.",
    autofix_hint="Point at a hashable (tuple/str/int) parameter; check "
                 "indices after signature changes."))

register_rule(Rule(
    id="DSR303", name="retrace-impure-capture", severity="warning",
    summary="jit-traced code mutates external Python state",
    rationale="global/self-attribute writes and module-level RNG calls "
              "inside a trace run ONCE at trace time, not per step: the "
              "mutation silently stops happening, and captured state "
              "goes stale across retraces.",
    autofix_hint="Thread state through function arguments/returns; use "
                 "jax.random with explicit keys."))

register_rule(Rule(
    id="DSR304", name="retrace-traced-branch", severity="warning",
    summary="Python if/while on a traced argument of a jitted callable",
    rationale="`if array:` forces bool() on a tracer "
              "(ConcretizationTypeError) — or, with static/weak types, "
              "silently traces only one branch.",
    autofix_hint="Use jnp.where / lax.cond / lax.select for data-"
                 "dependent control flow."))

register_rule(Rule(
    id="DSR305", name="retrace-unbucketed-length", severity="warning",
    summary="loop-varying array built inline at a jit boundary",
    rationale="An array constructed from loop-accumulated data "
              "(jnp.asarray over a growing list) changes SHAPE every "
              "iteration, so the jitted callee recompiles per length — "
              "the decode-loop bug where a serve retraces once per "
              "token instead of once per declared bucket.",
    autofix_hint="Pad to a declared bucket length before the jit "
                 "boundary (a helper named pad_*/bucket_* is recognized "
                 "as the fix)."))

# DSR305 machinery: array constructors whose result shape follows the
# data, loop-growth methods, and the helper-name markers that signal
# the shape was normalized to a declared bucket before the boundary
_ARRAY_CTORS = {"asarray", "array"}
_ARRAY_CTOR_OWNERS = {"jnp", "np", "numpy", "jax.numpy"}
_GROWTH_METHODS = {"append", "extend", "insert"}
_SHAPE_FIX_MARKERS = ("pad", "bucket")

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = {"dict", "list", "set", "bytearray", "defaultdict",
                  "Counter", "OrderedDict"}
_RNG_CALLS = {"random.random", "random.randint", "random.uniform",
              "random.choice", "random.shuffle", "random.seed"}
_NP_RNG_PREFIXES = ("np.random.", "numpy.random.")


def _is_mutable_default(node) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    return (isinstance(node, ast.Call)
            and call_name(node).rsplit(".", 1)[-1] in _MUTABLE_CTORS)


def _jit_call_targets(index: ModuleIndex):
    """(call_node, FuncNode, wrapper) for jit/pmap call-forms whose target
    resolves in-module — the sites where static_argnums can be checked."""
    enclosing = {}

    def mark(node, owner):
        for child in ast.iter_child_nodes(node):
            enclosing[id(child)] = owner
            own = index.node_map.get(id(child), owner) \
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)) else owner
            mark(child, own)

    mark(index.tree, None)
    out = []
    for call in ast.walk(index.tree):
        if not isinstance(call, ast.Call):
            continue
        leaf = call_name(call).rsplit(".", 1)[-1]
        if leaf not in ("jit", "pmap") or not call.args:
            continue
        target = index._resolve_callable_expr(call.args[0],
                                              enclosing.get(id(call)))
        if target is not None:
            out.append((call, target, leaf))
    return out


def _static_arg_diags(pf: ParsedFile, call: ast.Call, target) -> List:
    out = []
    params = target.params()
    defaults = target.defaults_by_param()
    # bound self.method references hide the self slot from argnums;
    # a plain in-class function passed by local name does not, but jit'd
    # inner functions in this codebase are closures, not methods — treat
    # the declared parameter list as the signature.
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            indices = []
            vals = (kw.value.elts if isinstance(kw.value, (ast.Tuple,
                                                           ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    indices.append(v.value)
            for idx in indices:
                if idx >= len(params) and not (target.node.args.vararg):
                    out.append(diag(
                        pf, kw.value, "DSR302",
                        f"static_argnums index {idx} is out of range for "
                        f"'{target.qualname}' ({len(params)} positional "
                        "parameters) — a stale index after a signature "
                        "change marks the wrong argument static"))
                elif idx < len(params):
                    d = defaults.get(params[idx])
                    if d is not None and _is_mutable_default(d):
                        out.append(diag(
                            pf, kw.value, "DSR302",
                            f"static_argnums marks parameter "
                            f"'{params[idx]}' of '{target.qualname}' "
                            "static, but its default is unhashable "
                            "(dict/list): TypeError at call time"))
        elif kw.arg == "static_argnames":
            names = []
            vals = (kw.value.elts if isinstance(kw.value, (ast.Tuple,
                                                           ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    names.append(v.value)
            all_names = params + [a.arg for a in target.node.args.kwonlyargs]
            for nm in names:
                if nm not in all_names and not target.node.args.kwarg:
                    out.append(diag(
                        pf, kw.value, "DSR302",
                        f"static_argnames names '{nm}' which is not a "
                        f"parameter of '{target.qualname}'"))
                else:
                    d = target.defaults_by_param().get(nm)
                    if d is not None and _is_mutable_default(d):
                        out.append(diag(
                            pf, kw.value, "DSR302",
                            f"static_argnames marks '{nm}' of "
                            f"'{target.qualname}' static, but its default "
                            "is unhashable (dict/list)"))
    return out


def _jit_boundary_names(index: ModuleIndex):
    """Plain names that ARE jit boundaries when called: targets of
    ``name = jax.jit(fn)`` assignments plus jit/pmap-decorated
    functions (a bare function later wrapped by call-form jit is NOT a
    boundary when called directly, so it is deliberately excluded)."""
    names = set()
    for node in ast.walk(index.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            leaf = call_name(node.value).rsplit(".", 1)[-1]
            if leaf in ("jit", "pmap"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                expr = dec.func if isinstance(dec, ast.Call) else dec
                if dotted_name(expr).rsplit(".", 1)[-1] in ("jit", "pmap"):
                    names.add(node.name)
    return names


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_shape_following_ctor(node):
    """jnp.asarray / np.array style calls: output shape follows input
    data, so a growing input means a new shape every call."""
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    owner, _, leaf = name.rpartition(".")
    return leaf in _ARRAY_CTORS and owner in _ARRAY_CTOR_OWNERS


def _has_shape_fix(node):
    """Whether the expression passes through a pad_*/ *_bucket* helper —
    the recognized 'length was normalized to a declared bucket' step."""
    for call in ast.walk(node):
        if isinstance(call, ast.Call):
            leaf = call_name(call).rsplit(".", 1)[-1].lower()
            if any(marker in leaf for marker in _SHAPE_FIX_MARKERS):
                return True
    return False


def _loop_dependent_names(loop):
    """Names whose value varies per loop iteration: the loop targets,
    anything grown in place (.append/.extend/+=), and — transitively —
    anything assigned from an expression over those."""
    dep = set()
    if isinstance(loop, ast.For):
        dep |= _names_in(loop.target)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(loop):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _GROWTH_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id not in dep):
                dep.add(node.func.value.id)
                changed = True
            elif (isinstance(node, ast.AugAssign)
                  and isinstance(node.target, ast.Name)
                  and node.target.id not in dep):
                dep.add(node.target.id)
                changed = True
            elif isinstance(node, ast.Assign) \
                    and _names_in(node.value) & dep:
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id not in dep:
                        dep.add(t.id)
                        changed = True
    return dep


def _unbucketed_ctor(expr, dep):
    """The shape-following array constructor inside ``expr`` that
    consumes loop-dependent data with no pad/bucket step, or None."""
    if _has_shape_fix(expr):
        return None
    for node in ast.walk(expr):
        if _is_shape_following_ctor(node) and _names_in(node) & dep:
            return node
    return None


def _unbucketed_length_diags(pf: ParsedFile, index: ModuleIndex) -> List:
    boundaries = _jit_boundary_names(index)
    if not boundaries:
        return []
    out = []
    for loop in ast.walk(index.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        dep = _loop_dependent_names(loop)
        if not dep:
            continue
        # names assigned (in this loop) from an unbucketed loop-shaped
        # array — passing one to a jitted callee fires the same way the
        # inline construction does
        tainted = set()
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign) \
                    and _unbucketed_ctor(node.value, dep) is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
        for call in ast.walk(loop):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)
                    and call.func.id in boundaries):
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            for arg in args:
                hit = _unbucketed_ctor(arg, dep)
                if hit is None and isinstance(arg, ast.Name) \
                        and arg.id in tainted:
                    hit = arg
                if hit is not None:
                    out.append(diag(
                        pf, hit, "DSR305",
                        f"array built from loop-varying data reaches "
                        f"jitted '{call.func.id}' without a declared "
                        "bucket: the callee recompiles once per length "
                        "(pad to a bucket before the jit boundary)"))
                    break
    return out


@register_file_checker
def check_retrace(pf: ParsedFile) -> List:
    index = ModuleIndex(pf.tree)
    out = []

    # DSR301/DSR304 apply to the direct jit entry points
    for fn in sorted(index.roots, key=lambda f: f.node.lineno):
        if isinstance(fn.node, ast.Lambda):
            continue
        nondefault_params = set(fn.params()) - set(fn.defaults_by_param())
        for pname, d in fn.defaults_by_param().items():
            if _is_mutable_default(d):
                out.append(diag(
                    pf, d, "DSR301",
                    f"parameter '{pname}' of jitted '{fn.qualname}' "
                    "defaults to a mutable dict/list/set: shared across "
                    "traces and unhashable as a static arg"))
        for node, _ in body_nodes(fn, index.node_map):
            if (isinstance(node, (ast.If, ast.While))
                    and isinstance(node.test, ast.Name)
                    and node.test.id in nondefault_params):
                out.append(diag(
                    pf, node, "DSR304",
                    f"Python branch on traced argument "
                    f"'{node.test.id}' in jitted '{fn.qualname}': bool() "
                    "of a tracer; use jnp.where/lax.cond"))

    # DSR303 applies to everything executing under a trace
    for fn in sorted(index.hot, key=lambda f: f.node.lineno):
        for node, _ in body_nodes(fn, index.node_map):
            if isinstance(node, ast.Global):
                out.append(diag(
                    pf, node, "DSR303",
                    f"'global {', '.join(node.names)}' inside jit-traced "
                    f"'{fn.qualname}': the write happens at trace time "
                    "only"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.append(diag(
                            pf, node, "DSR303",
                            f"assignment to self.{t.attr} inside "
                            f"jit-traced '{fn.qualname}': mutation runs "
                            "once at trace time, not per step"))
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in _RNG_CALLS or name.startswith(_NP_RNG_PREFIXES):
                    out.append(diag(
                        pf, node, "DSR303",
                        f"{name}() inside jit-traced '{fn.qualname}': "
                        "module-level RNG freezes at trace time; use "
                        "jax.random with explicit keys"))

    # DSR302 at jit call sites
    for call, target, _ in _jit_call_targets(index):
        out.extend(_static_arg_diags(pf, call, target))

    # DSR305: loop-varying lengths reaching a jit boundary unbucketed
    out.extend(_unbucketed_length_diags(pf, index))
    return out
