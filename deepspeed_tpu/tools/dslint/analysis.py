"""Module-level function index: scopes, call graph, jit-trace reachability.

The hot-path and retrace rule families both need the same question
answered: *which functions in this module execute under a jax trace?*
A function is trace-rooted when it is decorated with (or passed to) a
tracing wrapper — ``jax.jit``, ``shard_map``, ``pmap``, ``grad`` /
``value_and_grad``, ``checkpoint``/``remat``, or a ``lax`` control-flow
primitive — and everything reachable from a root through same-module
calls (including bare-name references, which cover ``lax.scan(body, …)``
styles) is *hot*.

Functions handed to host-callback escapes (``pure_callback``,
``io_callback``, ``jax.debug.*``) run on the HOST by design: they are
excluded from the hot set even when referenced from hot code.

Resolution is intentionally intra-module and name-based — no imports are
followed.  That keeps the linter fast and dependency-free; cross-module
reachability is approximated by every module's own roots being analyzed
when that module is linted.
"""

import ast
from typing import Dict, List, Optional, Set

from .core import dotted_name

# wrapper -> indices of positional args that are traced callables
# (None index = every element of a list/tuple arg, for lax.switch)
TRACE_WRAPPERS = {
    "jit": (0,), "pmap": (0,), "shard_map": (0,), "vmap": (0,),
    "grad": (0,), "value_and_grad": (0,), "checkpoint": (0,), "remat": (0,),
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,), "map": (0,),
    "cond": (1, 2), "switch": (1,), "custom_vjp": (0,), "custom_jvp": (0,),
}

# callables whose function arguments execute on the host, not in the trace
HOST_CALLBACK_WRAPPERS = {
    "pure_callback", "io_callback", "callback", "debug_callback",
}


# names too generic to trust without a 'lax' qualifier (builtin map(),
# dict-dispatch helpers named cond, ...)
_GENERIC_WRAPPER_NAMES = {"map", "cond"}


def _is_trace_wrapper(name: str) -> Optional[str]:
    """'jax.jit' / 'jit' / 'jax.lax.scan' -> terminal wrapper name."""
    leaf = name.rsplit(".", 1)[-1]
    if leaf not in TRACE_WRAPPERS:
        return None
    if leaf in _GENERIC_WRAPPER_NAMES and "lax" not in name:
        return None
    return leaf


def _is_host_callback(name: str) -> bool:
    leaf = name.rsplit(".", 1)[-1]
    return leaf in HOST_CALLBACK_WRAPPERS or name.startswith("jax.debug.")


class FuncNode:
    """One function/lambda definition in the module."""

    __slots__ = ("node", "name", "qualname", "class_name", "scope",
                 "parent", "is_property")

    def __init__(self, node, name, qualname, class_name, parent):
        self.node = node
        self.name = name
        self.qualname = qualname
        self.class_name = class_name   # enclosing class, if a method
        self.parent = parent           # enclosing FuncNode or None
        self.scope: Dict[str, "FuncNode"] = {}  # functions defined inside
        self.is_property = any(
            dotted_name(d) in ("property", "functools.cached_property",
                               "cached_property")
            for d in getattr(node, "decorator_list", []))

    def params(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    def defaults_by_param(self) -> Dict[str, ast.expr]:
        a = self.node.args
        pos = a.posonlyargs + a.args
        out = {}
        for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
            out[p.arg] = d
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None:
                out[p.arg] = d
        return out


class _Skip(Exception):
    pass


class ModuleIndex:
    """Scoped function index + trace-reachability for one module."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.functions: List[FuncNode] = []
        self.module_scope: Dict[str, FuncNode] = {}
        self.methods: Dict[str, Dict[str, FuncNode]] = {}  # class -> name->fn
        self.classes: List[ast.ClassDef] = []
        self.node_map: Dict[int, FuncNode] = {}  # id(ast node) -> FuncNode
        self._build(tree)
        self.roots: Set[FuncNode] = set()
        self.host_exempt: Set[FuncNode] = set()
        self._find_roots()
        self.hot: Set[FuncNode] = self._closure(self.roots)

    # -- construction ------------------------------------------------------

    def _build(self, tree):
        def walk(node, scope, class_name, parent):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    name = getattr(child, "name", "<lambda>")
                    qual = (f"{class_name}.{name}" if class_name else name)
                    fn = FuncNode(child, name, qual, class_name, parent)
                    self.functions.append(fn)
                    self.node_map[id(child)] = fn
                    scope[name] = scope.get(name, fn)  # first def wins
                    if class_name and parent is None:
                        self.methods.setdefault(class_name, {})[name] = fn
                    # function bodies open a new scope; decorators/defaults
                    # evaluate in the enclosing one
                    body = (child.body if not isinstance(child, ast.Lambda)
                            else [child.body])
                    for stmt in body if isinstance(body, list) else [body]:
                        walk(stmt, fn.scope, None, fn)
                elif isinstance(child, ast.ClassDef):
                    self.classes.append(child)
                    walk(child, {}, child.name, None)
                else:
                    walk(child, scope, class_name, parent)

        walk(tree, self.module_scope, None, None)

    # -- resolution --------------------------------------------------------

    def resolve_name(self, name: str,
                     from_fn: Optional[FuncNode]) -> Optional[FuncNode]:
        fn = from_fn
        while fn is not None:
            if name in fn.scope:
                return fn.scope[name]
            fn = fn.parent
        return self.module_scope.get(name)

    def resolve_self_attr(self, attr: str,
                          from_fn: Optional[FuncNode]) -> Optional[FuncNode]:
        fn = from_fn
        while fn is not None and fn.class_name is None:
            fn = fn.parent
        if fn is None:
            return None
        return self.methods.get(fn.class_name, {}).get(attr)

    def _resolve_callable_expr(self, expr,
                               from_fn: Optional[FuncNode]):
        if isinstance(expr, ast.Lambda):
            return self.node_map.get(id(expr))
        if isinstance(expr, ast.Name):
            return self.resolve_name(expr.id, from_fn)
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return self.resolve_self_attr(expr.attr, from_fn)
        return None

    # -- roots -------------------------------------------------------------

    def _decorator_roots(self, fn: FuncNode):
        for dec in getattr(fn.node, "decorator_list", []):
            name = dotted_name(dec)
            if not name and isinstance(dec, ast.Call):
                # @partial(jax.jit, ...) / @jax.jit(...) call-style
                inner = dotted_name(dec.func)
                if inner.rsplit(".", 1)[-1] == "partial" and dec.args:
                    name = dotted_name(dec.args[0])
                else:
                    name = inner
            if name and _is_trace_wrapper(name):
                return True
        return False

    def _find_roots(self):
        for fn in self.functions:
            if self._decorator_roots(fn):
                self.roots.add(fn)
        enclosing = {}  # id(node) -> FuncNode owning it lexically

        def mark(node, owner):
            for child in ast.iter_child_nodes(node):
                own = self.node_map.get(id(child), owner) \
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)) \
                    else owner
                enclosing[id(child)] = owner
                mark(child, own)

        mark(self.tree, None)

        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            name = dotted_name(call.func)
            owner = enclosing.get(id(call))
            if _is_host_callback(name):
                for arg in call.args:
                    target = self._resolve_callable_expr(arg, owner)
                    if target is not None:
                        self.host_exempt.add(target)
                continue
            wrapper = _is_trace_wrapper(name)
            if not wrapper:
                continue
            for idx in TRACE_WRAPPERS[wrapper]:
                if idx >= len(call.args):
                    continue
                arg = call.args[idx]
                if wrapper == "switch" and isinstance(arg, (ast.List,
                                                            ast.Tuple)):
                    cands = arg.elts
                else:
                    cands = [arg]
                for cand in cands:
                    target = self._resolve_callable_expr(cand, owner)
                    if target is not None:
                        self.roots.add(target)
        self.roots -= self.host_exempt

    # -- reachability ------------------------------------------------------

    def edges_from(self, fn: FuncNode) -> Set[FuncNode]:
        """Same-module call/reference edges from ``fn``'s own body (nested
        function bodies are their own nodes; host-callback arguments are
        not edges)."""
        out: Set[FuncNode] = set()

        def walk(node, top=False):
            if not top and id(node) in self.node_map:
                return  # nested def: its body is its own FuncNode
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if _is_host_callback(name):
                    walk(node.func)
                    return  # don't follow args into the host escape
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                target = self.resolve_name(node.id, fn)
                if target is not None:
                    out.add(target)
            elif (isinstance(node, ast.Attribute)
                  and isinstance(node.value, ast.Name)
                  and node.value.id == "self"):
                target = self.resolve_self_attr(node.attr, fn)
                if target is not None:
                    out.add(target)
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(fn.node, top=True)
        return out - {fn}

    def _closure(self, seeds: Set[FuncNode]) -> Set[FuncNode]:
        seen = set(seeds)
        frontier = list(seeds)
        while frontier:
            fn = frontier.pop()
            for nxt in self.edges_from(fn):
                if nxt not in seen and nxt not in self.host_exempt:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen


def body_nodes(fn: FuncNode, node_map):
    """Yield (node, in_loop) over ``fn``'s own body, excluding nested
    function/lambda bodies (they are separate FuncNodes).  ``in_loop`` is
    per-*iteration* precise: a ``for``'s iterable and a comprehension's
    first source evaluate once and are NOT in-loop."""

    def walk(node, in_loop, top=False):
        if not top and id(node) in node_map:
            return
        yield node, in_loop
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield from walk(node.target, in_loop)
            yield from walk(node.iter, in_loop)
            for child in node.body + node.orelse:
                yield from walk(child, True)
        elif isinstance(node, ast.While):
            yield from walk(node.test, True)
            for child in node.body + node.orelse:
                yield from walk(child, True)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            gens = node.generators
            yield from walk(gens[0].iter, in_loop)
            for g in gens:
                yield from walk(g.target, True)
                for cond in g.ifs:
                    yield from walk(cond, True)
            for g in gens[1:]:
                yield from walk(g.iter, True)
            if isinstance(node, ast.DictComp):
                yield from walk(node.key, True)
                yield from walk(node.value, True)
            else:
                yield from walk(node.elt, True)
        else:
            for child in ast.iter_child_nodes(node):
                yield from walk(child, in_loop)

    root = fn.node
    if isinstance(root, ast.Lambda):
        yield from walk(root.body, False)
    else:
        for stmt in root.body:
            yield from walk(stmt, False)
