"""dslint CLI.

    python -m deepspeed_tpu.tools.dslint deepspeed_tpu/
    python -m deepspeed_tpu.tools.dslint --config ds_config.json
    python -m deepspeed_tpu.tools.dslint --programs runs/telemetry
    python -m deepspeed_tpu.tools.dslint --list-rules
    python -m deepspeed_tpu.tools.dslint deepspeed_tpu/ --json report.json
    python -m deepspeed_tpu.tools.dslint deepspeed_tpu/ --sarif out.sarif
    python -m deepspeed_tpu.tools.dslint deepspeed_tpu/ \
        --baseline dslint_baseline.json [--update-baseline]

Exit status: 0 when no unsuppressed (and non-baselined) error/warning
diagnostics, 1 when violations exist, 2 on usage errors — including
unreadable/non-UTF8 source files and missing baseline/program dirs.

``--programs <run_dir>`` verifies the per-program artifacts a run
dumped under ``<run_dir>/programs/`` (optimized HLO + donation/mesh
sidecars, ``profiling.program_dump``) against the DSP6xx rules.

``--baseline <file>`` is the ratchet: known violations recorded in the
checked-in JSON stop failing the CLI — only NEW ones do.  Pair with
``--update-baseline`` to (re)record the current state.
"""

import argparse
import json
import os
import re
import sys
from collections import Counter
from typing import List

# rule modules register their checkers on import
from . import hotpath, programs, retrace, robustness  # noqa: F401
from .core import (Diagnostic, FAILING_SEVERITIES, FAMILY_BUDGETS, RULES,
                   ParsedFile, SourceReadError, check_file, rule_catalog,
                   rule_family)
from .schema import (dead_key_diagnostics, get_schema,
                     issues_to_diagnostics, validate_config_dict)

# version of the --json report format (bumped on breaking shape change)
JSON_SCHEMA_VERSION = 1
BASELINE_SCHEMA_VERSION = 1


def iter_python_files(paths) -> List[str]:
    out = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git", "build",
                                            "node_modules")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        else:
            raise FileNotFoundError(path)
    return sorted(set(out))


def lint_paths(paths, select=None, ignore=None) -> List[Diagnostic]:
    """Lint files/dirs; returns all diagnostics (suppressed ones marked)."""
    return lint_files(iter_python_files(paths), select=select,
                      ignore=ignore)


def lint_files(files, select=None, ignore=None) -> List[Diagnostic]:
    """Lint an explicit file list.

    The dead-key cross-check runs once when the scanned set includes the
    package's ``runtime/constants.py`` (i.e. when linting the package
    itself rather than a stray file).  Raises :class:`SourceReadError`
    (CLI: exit 2) for a file that cannot be read or is not UTF-8.
    """
    diags: List[Diagnostic] = []
    constants_file = None
    for path in files:
        try:
            pf = ParsedFile.parse(path)
        except SyntaxError as e:
            diags.append(Diagnostic(path=path, line=e.lineno or 1, col=1,
                                    rule_id="DSC402",
                                    message=f"file does not parse: {e.msg}"))
            continue
        except (OSError, UnicodeDecodeError, ValueError) as e:
            raise SourceReadError(path, e) from e
        diags.extend(check_file(pf))
        norm = path.replace(os.sep, "/")
        if norm.endswith("runtime/constants.py"):
            constants_file = os.path.abspath(path)
    if constants_file is not None:
        pkg_root = os.path.dirname(os.path.dirname(constants_file))
        dead = dead_key_diagnostics(pkg_root)
        src = open(constants_file, "r", encoding="utf-8").read()
        pf = ParsedFile.parse(constants_file, src)
        pf.apply_suppressions(dead)
        diags.extend(dead)
    if select:
        diags = [d for d in diags if d.rule_id in select]
    if ignore:
        diags = [d for d in diags if d.rule_id not in ignore]
    return diags


def failing(diags) -> List[Diagnostic]:
    return [d for d in diags
            if not d.suppressed and d.severity in FAILING_SEVERITIES]


def lint_config_files(paths) -> List[Diagnostic]:
    diags = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                cfg = json.load(f)
        except (OSError, ValueError) as e:
            diags.append(Diagnostic(path=path, line=1, col=1,
                                    rule_id="DSC402",
                                    message=f"config does not load: {e}"))
            continue
        diags.extend(issues_to_diagnostics(validate_config_dict(cfg), path))
    return diags


def lint_program_dirs(run_dirs):
    """(diagnostics, artifacts, by_dir): DSP6xx verification of dumped
    program artifacts (see ``tools/dslint/programs.py``).  Raises
    FileNotFoundError when a run dir holds no artifacts (usage error,
    exit 2).  The artifacts come back too: the baseline's metric
    ratchets (DSO704 exposed wire, DSO705 attribution) re-analyze them
    against the recorded figures — DSO705 per run dir, because the
    measured-latency evidence lives next to the sidecars."""
    diags: List[Diagnostic] = []
    artifacts = []
    by_dir = []
    for run_dir in run_dirs:
        loaded = programs.load_run_artifacts(run_dir)
        artifacts.extend(loaded)
        by_dir.append((run_dir, loaded))
        diags.extend(programs.verify_artifacts(loaded))
    return diags, artifacts, by_dir


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------

_PROGRAM_DIAG_RE = re.compile(r"^\[(?P<program>[^\]]+)\] ")


def baseline_key(d: Diagnostic) -> str:
    """Stable identity of one violation for the ratchet: path + rule +
    message (NOT line numbers, which drift with unrelated edits).

    Program-verifier (DSP6xx/DSO7xx artifact) diagnostics key on the
    PROGRAM name + rule only: their paths embed the run dir and their messages
    embed byte counts, both of which change run to run — a baselined
    intentional psum must keep matching after a re-dump or a model
    resize (the ratchet is the only suppression mechanism for program
    findings; they have no source line to pragma)."""
    m = _PROGRAM_DIAG_RE.match(d.message)
    if m and d.rule_id.startswith(("DSP6", "DSO7", "DSS8")):
        return f"<programs>|{d.rule_id}|{m.group('program')}"
    return f"{d.path.replace(os.sep, '/')}|{d.rule_id}|{d.message}"


def load_baseline(path) -> Counter:
    return load_baseline_data(path)[0]


def load_baseline_data(path):
    """(violation Counter, metrics dict).  ``metrics`` holds the
    ratcheted per-program figures (``<programs>|exposed_wire_seconds|
    <name>`` -> seconds) that ``--update-baseline`` records and the
    DSO704 exposed-wire ratchet checks."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    violations = data.get("violations") if isinstance(data, dict) else None
    if violations is None:
        violations = {}
    if not isinstance(violations, dict):
        raise ValueError(
            f"baseline {path}: 'violations' must be an object of "
            f"key -> count, got {type(violations).__name__}")
    metrics = data.get("metrics") if isinstance(data, dict) else None
    if metrics is None:
        metrics = {}
    if not isinstance(metrics, dict):
        raise ValueError(
            f"baseline {path}: 'metrics' must be an object of "
            f"key -> number, got {type(metrics).__name__}")
    try:
        metrics = {str(k): float(v) for k, v in metrics.items()}
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"baseline {path}: metric values must be numbers "
            f"({e})") from e
    try:
        return Counter({str(k): int(v)
                        for k, v in violations.items()}), metrics
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"baseline {path}: violation counts must be integers "
            f"({e})") from e


def write_baseline(path, fail, metrics=None) -> dict:
    data = {
        "schema_version": BASELINE_SCHEMA_VERSION,
        "violations": dict(sorted(Counter(
            baseline_key(d) for d in fail).items())),
        "metrics": dict(sorted((metrics or {}).items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


def apply_baseline(fail, baseline: Counter):
    """(new_violations, baselined_count): occurrences beyond the
    baselined count of their key still fail (a second instance of a
    known violation is NEW)."""
    budget = Counter(baseline)
    new, baselined = [], 0
    for d in fail:
        key = baseline_key(d)
        if budget[key] > 0:
            budget[key] -= 1
            baselined += 1
        else:
            new.append(d)
    return new, baselined


def _by_family(diags):
    return dict(sorted(Counter(rule_family(d.rule_id)
                               for d in diags).items()))


# ---------------------------------------------------------------------------
# SARIF 2.1.0 output (CI inline annotations)
# ---------------------------------------------------------------------------

SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                 "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
_SARIF_LEVELS = {"error": "error", "warning": "warning", "info": "note"}


def sarif_report(diags, new_fail) -> dict:
    """One SARIF 2.1.0 run covering source AND program diagnostics.

    Every diagnostic becomes a result; pragma-suppressed ones carry a
    ``suppressions`` entry of kind ``inSource`` and baselined ones kind
    ``external``.  Info-severity diagnostics (DSP602 downgrades) emit
    as level ``note`` with no suppressions — informational, never
    exit-code-driving — so the invariant round-trip-tested against
    ``--json`` is: unsuppressed ``error``/``warning`` results ==
    ``violations``."""
    new_ids = {id(d) for d in new_fail}
    rule_ids = sorted(RULES)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for d in diags:
        result = {
            "ruleId": d.rule_id,
            "ruleIndex": rule_index[d.rule_id],
            "level": _SARIF_LEVELS[d.severity],
            "message": {"text": d.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": d.path.replace(os.sep, "/")},
                    "region": {"startLine": max(int(d.line), 1),
                               "startColumn": max(int(d.col), 1)},
                }}],
        }
        if d.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        elif (d.severity in FAILING_SEVERITIES
              and id(d) not in new_ids):
            result["suppressions"] = [{"kind": "external"}]
        results.append(result)
    return {
        "$schema": _SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "dslint",
                "informationUri":
                    "https://github.com/deepspeed-tpu/deepspeed-tpu",
                "rules": [{
                    "id": rid,
                    "name": RULES[rid].name,
                    "shortDescription": {"text": RULES[rid].summary},
                    "fullDescription": {"text": RULES[rid].rationale},
                    "defaultConfiguration": {
                        "level": _SARIF_LEVELS[RULES[rid].severity]},
                } for rid in rule_ids],
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dslint",
        description="TPU-correctness static analysis for DeepSpeed-TPU: "
                    "hot-path host-sync rules, retrace-hazard rules, "
                    "config-schema validation, and program-level "
                    "donation/collective-semantics verification "
                    "(DSP6xx) over dumped compile artifacts.")
    ap.add_argument("paths", nargs="*",
                    help="python files/directories to lint")
    ap.add_argument("--config", action="append", default=[],
                    metavar="JSON",
                    help="validate a DeepSpeed JSON config file against "
                         "the extracted schema")
    ap.add_argument("--programs", action="append", default=[],
                    metavar="RUN_DIR",
                    help="verify per-program artifacts dumped under "
                         "RUN_DIR/programs/ (profiling.program_dump) "
                         "against the DSP6xx rules")
    ap.add_argument("--json", metavar="FILE", dest="json_out",
                    help="write a machine-readable report (carries a "
                         "stable schema_version field)")
    ap.add_argument("--sarif", metavar="FILE", dest="sarif_out",
                    help="write a SARIF 2.1.0 report covering source "
                         "AND program findings (CI inline annotations); "
                         "exit codes unchanged")
    ap.add_argument("--baseline", metavar="FILE",
                    help="ratchet mode: violations recorded in FILE do "
                         "not fail; only NEW ones do")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current violations to --baseline "
                         "FILE (exit 0)")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--ignore", metavar="IDS",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print pragma-suppressed diagnostics")
    ap.add_argument("--all", action="store_true", dest="run_all",
                    help="the composite CI gate: lint the shipped "
                         "package source, apply the checked-in "
                         "tools/dslint_baseline.json ratchet, and "
                         "verify the checked-in fixture program "
                         "sidecars under tools/dslint_fixtures/ — one "
                         "invocation, so the three gates cannot drift "
                         "apart")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(rule_catalog())
        return 0
    if args.run_all:
        # repo layout anchor: cli.py lives at
        # <repo>/deepspeed_tpu/tools/dslint/cli.py
        pkg = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        repo = os.path.dirname(pkg)
        if not args.paths:
            args.paths = [pkg]
        if not args.baseline:
            args.baseline = os.path.join(repo, "tools",
                                         "dslint_baseline.json")
        fixtures = os.path.join(repo, "tools", "dslint_fixtures")
        if os.path.isdir(fixtures):
            args.programs = list(args.programs) + sorted(
                os.path.join(fixtures, d) for d in os.listdir(fixtures)
                if os.path.isdir(os.path.join(fixtures, d)))
    if not args.paths and not args.config and not args.programs:
        ap.print_usage(sys.stderr)
        return 2
    if args.update_baseline and not args.baseline:
        print("dslint: --update-baseline requires --baseline FILE",
              file=sys.stderr)
        return 2

    select = set(args.select.split(",")) if args.select else None
    ignore = set(args.ignore.split(",")) if args.ignore else None
    try:
        files = iter_python_files(args.paths) if args.paths else []
    except FileNotFoundError as e:
        print(f"dslint: no such path: {e}", file=sys.stderr)
        return 2
    try:
        diags = lint_files(files, select=select, ignore=ignore)
    except SourceReadError as e:
        print(f"dslint: {e}", file=sys.stderr)
        return 2
    diags.extend(lint_config_files(args.config))
    try:
        prog_diags, prog_artifacts, prog_by_dir = lint_program_dirs(
            args.programs)
    except (FileNotFoundError, OSError, ValueError) as e:
        print(f"dslint: cannot load program artifacts: {e}",
              file=sys.stderr)
        return 2
    programs_checked = len(prog_artifacts)
    if select:
        prog_diags = [d for d in prog_diags if d.rule_id in select]
    if ignore:
        prog_diags = [d for d in prog_diags if d.rule_id not in ignore]
    diags.extend(prog_diags)
    diags.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))

    fail = failing(diags)
    suppressed = [d for d in diags if d.suppressed]

    baseline = None
    baselined = 0
    if args.baseline:
        if args.update_baseline:
            metrics = programs.exposure_metrics(prog_artifacts)
            metrics.update(programs.sharding_metrics(prog_artifacts))
            for run_dir, dir_artifacts in prog_by_dir:
                metrics.update(programs.attribution_metrics(
                    dir_artifacts, run_dir=run_dir))
            write_baseline(args.baseline, fail, metrics=metrics)
            print(f"dslint: baseline updated: {len(fail)} violation(s) "
                  f"recorded to {args.baseline}")
            baseline = Counter(baseline_key(d) for d in fail)
            fail, baselined = [], len(fail)
        else:
            try:
                baseline, base_metrics = load_baseline_data(args.baseline)
            except (OSError, ValueError) as e:
                print(f"dslint: cannot read --baseline {args.baseline}: "
                      f"{e}", file=sys.stderr)
                return 2
            fail, baselined = apply_baseline(fail, baseline)
            # metric ratchets: recorded figures only tighten — growth
            # (DSO704 exposed wire, DSS803 per-device parameter bytes)
            # or reconciliation drift (DSO705 attribution) past
            # tolerance is a NEW violation the violations baseline
            # cannot absolve
            ratchet = programs.check_exposure_ratchet(prog_artifacts,
                                                      base_metrics)
            ratchet.extend(programs.check_attribution_ratchet(
                prog_by_dir, base_metrics))
            ratchet.extend(programs.check_sharding_ratchet(
                prog_artifacts, base_metrics))
            if select:
                ratchet = [d for d in ratchet if d.rule_id in select]
            if ignore:
                ratchet = [d for d in ratchet if d.rule_id not in ignore]
            diags.extend(ratchet)
            fail.extend(ratchet)

    for d in diags:
        if d.suppressed and not args.show_suppressed:
            continue
        print(d.format())
    tail = f", {baselined} baselined" if args.baseline else ""
    print(f"dslint: {len(fail)} violation(s), {len(suppressed)} "
          f"suppressed{tail}, {len(files)} file(s) scanned, "
          f"{len(RULES)} rules")

    if args.sarif_out:
        with open(args.sarif_out, "w", encoding="utf-8") as f:
            json.dump(sarif_report(diags, fail), f, indent=2,
                      sort_keys=True)

    if args.json_out:
        report = {
            "schema_version": JSON_SCHEMA_VERSION,
            "violations": len(fail),
            "violations_by_family": _by_family(fail),
            "suppressed": len(suppressed),
            "suppressed_by_family": _by_family(suppressed),
            # the per-family pragma budgets the tier-1 self-test
            # enforces (program families DSP6/DSO7 are 0: baseline-
            # ratchet only)
            "family_budgets": dict(FAMILY_BUDGETS),
            "baselined": baselined,
            "baseline_file": args.baseline,
            "files_scanned": len(files),
            "program_dirs": list(args.programs),
            "programs_checked": programs_checked,
            "schema_keys": len(get_schema().all_keys()),
            "diagnostics": [d.to_json() for d in diags],
            "rules": {r.id: {"name": r.name, "severity": r.severity,
                             "summary": r.summary}
                      for r in RULES.values()},
        }
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    return 1 if fail else 0


if __name__ == "__main__":
    sys.exit(main())
