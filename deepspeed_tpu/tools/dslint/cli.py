"""dslint CLI.

    python -m deepspeed_tpu.tools.dslint deepspeed_tpu/
    python -m deepspeed_tpu.tools.dslint --config ds_config.json
    python -m deepspeed_tpu.tools.dslint --list-rules
    python -m deepspeed_tpu.tools.dslint deepspeed_tpu/ --json report.json

Exit status: 0 when no unsuppressed error/warning diagnostics, 1 when
violations exist, 2 on usage/parse errors.
"""

import argparse
import json
import os
import sys
from typing import List

# rule modules register their checkers on import
from . import hotpath, retrace, robustness  # noqa: F401
from .core import (Diagnostic, FAILING_SEVERITIES, RULES, ParsedFile,
                   check_file, rule_catalog)
from .schema import (dead_key_diagnostics, get_schema,
                     issues_to_diagnostics, validate_config_dict)


def iter_python_files(paths) -> List[str]:
    out = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git", "build",
                                            "node_modules")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        else:
            raise FileNotFoundError(path)
    return sorted(set(out))


def lint_paths(paths, select=None, ignore=None) -> List[Diagnostic]:
    """Lint files/dirs; returns all diagnostics (suppressed ones marked)."""
    return lint_files(iter_python_files(paths), select=select,
                      ignore=ignore)


def lint_files(files, select=None, ignore=None) -> List[Diagnostic]:
    """Lint an explicit file list.

    The dead-key cross-check runs once when the scanned set includes the
    package's ``runtime/constants.py`` (i.e. when linting the package
    itself rather than a stray file).
    """
    diags: List[Diagnostic] = []
    constants_file = None
    for path in files:
        try:
            pf = ParsedFile.parse(path)
        except SyntaxError as e:
            diags.append(Diagnostic(path=path, line=e.lineno or 1, col=1,
                                    rule_id="DSC402",
                                    message=f"file does not parse: {e.msg}"))
            continue
        diags.extend(check_file(pf))
        norm = path.replace(os.sep, "/")
        if norm.endswith("runtime/constants.py"):
            constants_file = os.path.abspath(path)
    if constants_file is not None:
        pkg_root = os.path.dirname(os.path.dirname(constants_file))
        dead = dead_key_diagnostics(pkg_root)
        src = open(constants_file, "r", encoding="utf-8").read()
        pf = ParsedFile.parse(constants_file, src)
        pf.apply_suppressions(dead)
        diags.extend(dead)
    if select:
        diags = [d for d in diags if d.rule_id in select]
    if ignore:
        diags = [d for d in diags if d.rule_id not in ignore]
    return diags


def failing(diags) -> List[Diagnostic]:
    return [d for d in diags
            if not d.suppressed and d.severity in FAILING_SEVERITIES]


def lint_config_files(paths) -> List[Diagnostic]:
    diags = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                cfg = json.load(f)
        except (OSError, ValueError) as e:
            diags.append(Diagnostic(path=path, line=1, col=1,
                                    rule_id="DSC402",
                                    message=f"config does not load: {e}"))
            continue
        diags.extend(issues_to_diagnostics(validate_config_dict(cfg), path))
    return diags


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dslint",
        description="TPU-correctness static analysis for DeepSpeed-TPU: "
                    "hot-path host-sync rules, retrace-hazard rules, and "
                    "config-schema validation.")
    ap.add_argument("paths", nargs="*",
                    help="python files/directories to lint")
    ap.add_argument("--config", action="append", default=[],
                    metavar="JSON",
                    help="validate a DeepSpeed JSON config file against "
                         "the extracted schema")
    ap.add_argument("--json", metavar="FILE", dest="json_out",
                    help="write a machine-readable report")
    ap.add_argument("--select", metavar="IDS",
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--ignore", metavar="IDS",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print pragma-suppressed diagnostics")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(rule_catalog())
        return 0
    if not args.paths and not args.config:
        ap.print_usage(sys.stderr)
        return 2

    select = set(args.select.split(",")) if args.select else None
    ignore = set(args.ignore.split(",")) if args.ignore else None
    try:
        files = iter_python_files(args.paths) if args.paths else []
    except FileNotFoundError as e:
        print(f"dslint: no such path: {e}", file=sys.stderr)
        return 2
    diags = lint_files(files, select=select, ignore=ignore)
    diags.extend(lint_config_files(args.config))
    diags.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))

    fail = failing(diags)
    suppressed = [d for d in diags if d.suppressed]
    for d in diags:
        if d.suppressed and not args.show_suppressed:
            continue
        print(d.format())
    print(f"dslint: {len(fail)} violation(s), {len(suppressed)} "
          f"suppressed, {len(files)} file(s) scanned, "
          f"{len(RULES)} rules")

    if args.json_out:
        report = {
            "violations": len(fail),
            "suppressed": len(suppressed),
            "files_scanned": len(files),
            "schema_keys": len(get_schema().all_keys()),
            "diagnostics": [d.to_json() for d in diags],
            "rules": {r.id: {"name": r.name, "severity": r.severity,
                             "summary": r.summary}
                      for r in RULES.values()},
        }
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    return 1 if fail else 0


if __name__ == "__main__":
    sys.exit(main())
