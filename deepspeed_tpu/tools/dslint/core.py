"""dslint core: rule registry, diagnostics, suppression pragmas, file model.

``dslint`` is an AST-level linter for the TPU-correctness hazards that
JSON-dict config systems and jit-compiled training loops make *silent*:
a misspelled config key quietly reverts to its default, a stray
``.item()`` inside a compiled step quietly costs a device→host round
trip every step, and a retrace hazard quietly recompiles a minute-long
program.  Rules are small, registered objects so a new hazard class is a
~20-line addition (see ``docs/static_analysis.md``).

Everything in this package is stdlib-only (``ast`` + ``tokenize``-free
line scanning): the linter must run in CI images and pre-commit hooks
that have no jax installed.
"""

import ast
import dataclasses
import re
from typing import Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

SEVERITIES = ("error", "warning", "info")
# severities that make the CLI exit non-zero when unsuppressed
FAILING_SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One checkable hazard class."""

    id: str                 # e.g. "DSH101"
    name: str               # kebab-case slug, e.g. "hot-item-sync"
    severity: str           # "error" | "warning" | "info"
    summary: str            # one-line message template context
    rationale: str          # why this is a TPU-correctness hazard
    autofix_hint: str = ""  # how a human (or tool) repairs it

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity


RULES: Dict[str, Rule] = {}


def register_rule(rule: Rule) -> Rule:
    assert rule.id not in RULES, f"duplicate rule id {rule.id}"
    RULES[rule.id] = rule
    return rule


def rule_family(rule_id: str) -> str:
    """'DSH203' -> 'DSH2': the per-family budget/reporting key (family
    = letter prefix + leading digit of the hundreds block)."""
    return rule_id[:4]


# Per-family pragma suppression budgets: the number of reasoned
# `# dslint: disable=` pragmas each rule family may carry in the
# shipped tree.  Enforced by the tier-1 self-test
# (tests/unit/test_dslint_self.py) and reported by `--json` /
# `--list-rules`.  Program-level families (DSP6 donation/collective
# semantics, DSO7 overlap/exposed-wire) have NO pragma budget by
# construction — program findings carry no source line to pragma; the
# `--baseline` ratchet is their only suppression mechanism.
FAMILY_BUDGETS = {
    "DSC4": 1,   # config dead-key (wired-by-reference constant)
    "DSH1": 2,   # partial-bound static casts
    "DSH2": 4,   # print-cadence driver fetches (1 spare for the class)
    "DSR3": 0,   # retrace hazards: fix them, never pragma them
    "DSE5": 7,   # optional-backend probes
    "DSP6": 0,   # program verifier: ratchet via --baseline or fix
    "DSO7": 0,   # overlap analyzer: ratchet via --baseline or fix
    "DSS8": 0,   # sharding auditor: ratchet via --baseline or fix
}


class SourceReadError(Exception):
    """A source file could not be read (missing, unreadable, or not
    UTF-8) — a usage-class failure (CLI exit 2), distinct from a
    file that reads fine but does not parse (DSC402 diagnostic)."""

    def __init__(self, path, err):
        super().__init__(f"cannot read {path}: {err}")
        self.path = path
        self.err = err


@dataclasses.dataclass
class Diagnostic:
    """One finding at a source location."""

    path: str
    line: int
    col: int
    rule_id: str
    message: str
    end_line: Optional[int] = None
    suppressed: bool = False

    @property
    def severity(self) -> str:
        return RULES[self.rule_id].severity

    def format(self) -> str:
        state = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule_id} "
                f"[{self.severity}]{state} {self.message}")

    def to_json(self) -> dict:
        return {
            "path": self.path, "line": self.line, "col": self.col,
            "rule": self.rule_id, "severity": self.severity,
            "message": self.message, "suppressed": self.suppressed,
        }


# ---------------------------------------------------------------------------
# Suppression pragmas
# ---------------------------------------------------------------------------
#
#   x = arr.item()          # dslint: disable=DSH101 -- reason (optional)
#   # dslint: disable=DSH101,DSC401   <- standalone: applies to next line
#
# A pragma suppresses matching diagnostics on its own physical line; a
# standalone (comment-only) pragma line additionally covers the line below
# it, so long statements can carry the pragma above themselves.

_PRAGMA_RE = re.compile(
    r"#\s*dslint:\s*disable=([A-Za-z0-9_*,\s]+?)(?:\s*--.*)?$")


def collect_pragmas(source: str) -> Dict[int, set]:
    """Map line number (1-based) -> set of suppressed rule ids ('all' ok)."""
    pragmas: Dict[int, set] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        ids = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
        pragmas.setdefault(i, set()).update(ids)
        if line.lstrip().startswith("#"):
            # standalone pragma line: also covers the statement below
            pragmas.setdefault(i + 1, set()).update(ids)
    return pragmas


def is_suppressed(pragmas: Dict[int, set], rule_id: str, line: int,
                  end_line: Optional[int] = None) -> bool:
    """A diagnostic is suppressed when any physical line of its statement
    carries a matching pragma."""
    for ln in range(line, (end_line or line) + 1):
        ids = pragmas.get(ln)
        if ids and (rule_id in ids or "all" in ids):
            return True
    return False


# ---------------------------------------------------------------------------
# Parsed-file model + checker registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ParsedFile:
    path: str
    source: str
    tree: ast.AST
    pragmas: Dict[int, set]

    @classmethod
    def parse(cls, path: str, source: Optional[str] = None) -> "ParsedFile":
        if source is None:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        tree = ast.parse(source, filename=path)
        return cls(path=path, source=source, tree=tree,
                   pragmas=collect_pragmas(source))

    def apply_suppressions(self,
                           diags: List[Diagnostic]) -> List[Diagnostic]:
        for d in diags:
            d.suppressed = is_suppressed(self.pragmas, d.rule_id, d.line,
                                         d.end_line)
        return diags


# per-file checkers: fn(ParsedFile) -> list[Diagnostic]
FILE_CHECKERS: List[Callable[[ParsedFile], List[Diagnostic]]] = []


def register_file_checker(fn):
    FILE_CHECKERS.append(fn)
    return fn


def check_file(pf: ParsedFile) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for checker in FILE_CHECKERS:
        diags.extend(checker(pf))
    pf.apply_suppressions(diags)
    diags.sort(key=lambda d: (d.line, d.col, d.rule_id))
    return diags


# ---------------------------------------------------------------------------
# Small AST helpers shared by the rule modules
# ---------------------------------------------------------------------------

def dotted_name(node) -> str:
    """'jax.lax.scan' for nested Attribute/Name chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def diag(pf: ParsedFile, node, rule_id: str, message: str) -> Diagnostic:
    return Diagnostic(path=pf.path, line=node.lineno,
                      col=getattr(node, "col_offset", 0) + 1,
                      rule_id=rule_id, message=message,
                      end_line=getattr(node, "end_lineno", None))


def rule_catalog() -> str:
    """Human-readable rule table (also: ``--list-rules``)."""
    lines = []
    for rule in sorted(RULES.values(), key=lambda r: r.id):
        lines.append(f"{rule.id} [{rule.severity}] {rule.name}")
        lines.append(f"    {rule.summary}")
        lines.append(f"    why: {rule.rationale}")
        if rule.autofix_hint:
            lines.append(f"    fix: {rule.autofix_hint}")
    lines.append("suppression budgets (pragmas per family; 0 = "
                 "baseline-ratchet only):")
    lines.append("    " + "  ".join(f"{fam}xx={n}" for fam, n in
                                    sorted(FAMILY_BUDGETS.items())))
    return "\n".join(lines)
