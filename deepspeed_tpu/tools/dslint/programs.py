"""Program-level semantic rules (DSP6xx): donation/aliasing safety and
collective semantics, checked on the COMPILED program.

dslint's other rule families lint Python ASTs; the two real bugs this
repo has shipped lived below what any AST rule can see — in the
optimized HLO that XLA/GSPMD emits:

- the ZeRO flatten that psum-SUMMED parameters across the tensor-
  parallel axis on every dp×tp mesh (finite loss masked it for eight
  rounds; caught only by the runtime dp=1 parity assert, PR 8);
- the donated ``device_put`` of a live numpy staging buffer that
  flakily corrupted the glibc heap on the second train step.

Both are *statically decidable* from artifacts the stack already
captures at AOT-compile time (the MemoryLedger/CommLedger hook walks
``compiled.as_text()`` once per program): donation shows up as the
module-header ``input_output_alias`` map, and a wrong-mesh-axis sum
shows up as an ``all-reduce`` whose replica groups span more devices
than the data axis.  This module turns each into a rule, so the next
instance is a CI failure instead of a 2-AM loss divergence.

Two analysis surfaces:

- **HLO artifacts** (:class:`ProgramArtifact` + :func:`verify_program`)
  — built live by ``engine.verify_programs()``
  (``profiling/verify.py``) or loaded from the ``<run_dir>/programs/``
  dump via ``python -m deepspeed_tpu.tools.dslint --programs
  <run_dir>``;
- **Python source** (the DSP603 dataflow checker registered below) —
  an AST companion that flags driver code reading a buffer after it
  was passed to a donating jit call (the heap-corruption shape).

Like the rest of dslint, this module is stdlib-only; the HLO collective
parser is borrowed lazily from ``profiling/comm.py`` (itself
stdlib+regex) so the ring-model accounting has exactly one
implementation.
"""

import ast
import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from .core import (Diagnostic, ParsedFile, Rule, call_name, diag,
                   register_file_checker, register_rule)

# artifact sidecar format version (``<run_dir>/programs/<name>.json``)
ARTIFACT_SCHEMA_VERSION = 1
PROGRAMS_DIRNAME = "programs"

# -- rule catalog -----------------------------------------------------------

register_rule(Rule(
    id="DSP601", name="donation-not-materialized", severity="error",
    summary="jit entry point declares donate_argnums but the compiled "
            "executable materialized no input→output aliases",
    rationale="Donation is a capacity contract: the engine sizes HBM "
              "assuming state buffers are reused in place.  A program "
              "that silently drops every alias (dtype/sharding mismatch, "
              "backend limitation) doubles its state footprint and the "
              "capacity planner's verdict is wrong.",
    autofix_hint="Check that donated arguments' shapes/dtypes/shardings "
                 "match the outputs they should alias; see the "
                 "input_output_alias header of the dumped HLO."))

register_rule(Rule(
    id="DSP602", name="donation-unverifiable", severity="info",
    summary="donation aliases present in HLO but memory_analysis "
            "reports alias=0 (warm-cache deserialization caveat)",
    rationale="Executables deserialized from the persistent compile "
              "cache can report alias_size_in_bytes=0 even though the "
              "program text declares its input_output_alias map (PR 7 "
              "measured caveat, docs/observability.md).  Structural "
              "aliasing IS verified from the text; only the byte "
              "accounting is unverifiable — an explicit downgraded "
              "verdict, never silence.",
    autofix_hint="Cold-compile (clear the XLA cache) to re-verify the "
                 "byte accounting; predicted peaks are conservative "
                 "meanwhile."))

register_rule(Rule(
    id="DSP603", name="use-after-donation", severity="error",
    summary="a buffer reference is read after being passed to a "
            "donating jit call",
    rationale="A donated buffer is dead the moment the call is issued: "
              "XLA may reuse its memory for the outputs.  Reading the "
              "Python reference afterwards observes garbage — and when "
              "the donated value is a device_put of a live numpy "
              "staging buffer, the runtime can free numpy-owned memory "
              "and corrupt the allocator heap (observed: flaky glibc "
              "aborts on the 2nd train step, PR 8).",
    autofix_hint="Drop the reference after the donating call, or "
                 "re-home device_put results through a jitted copy so "
                 "the XLA allocator owns the donated buffer."))

register_rule(Rule(
    id="DSP611", name="param-sum-over-non-data-axis", severity="error",
    summary="cross-replica all-reduce sums a parameter-sized tensor "
            "over replica groups spanning a non-data mesh axis",
    rationale="Non-data mesh axes (model/pipe/seq/expert) hold REPLICAS "
              "of unsharded parameters, not partial values: an "
              "all-reduce whose groups span them multiplies every "
              "parameter by the axis product.  This is the flatten-×tp "
              "bug — loss stays finite (~ln vocab), so nothing "
              "downstream fails loudly.  Scope: the rule fires only "
              "when the full-mesh sum is the program's ONLY collective "
              "shape — the standalone init/flatten program signature.  "
              "Inside step programs GSPMD legitimately emits full-mesh "
              "assembly all-reduces over partition-exact "
              "dynamic-update-slice writes (measured parity-exact on "
              "this toolchain); those programs always carry data-axis-"
              "scoped collectives alongside and are exempt — the "
              "multichip dp=1 parity asserts remain their gate.",
    autofix_hint="Reduce over the data axis only (psum with the axis "
                 "name), or build the buffer host-side as "
                 "flatten_to_master now does."))

register_rule(Rule(
    id="DSP612", name="psum-for-pmean-suspect", severity="warning",
    summary="scalar cross-replica all-reduce with no mean-compensation "
            "scaling constant anywhere in the program",
    rationale="Step semantics for losses/metrics exchanged across data "
              "replicas almost always require a MEAN; a bare psum "
              "scales them by the group size and trains on a silently "
              "multiplied signal.  Heuristic: a correct pmean (or a "
              "global-batch-normalized loss) leaves a 1/k scaling "
              "constant with the group size dividing k in the "
              "optimized HLO; its absence is the psum signature.",
    autofix_hint="Use jax.lax.pmean (or divide by the axis size); if "
                 "the sum is intentional (e.g. a grad-norm psum), "
                 "ratchet it via `--baseline`."))

register_rule(Rule(
    id="DSP614", name="collective-analysis-unavailable",
    severity="warning",
    summary="the HLO collective parser (profiling/comm.py) could not "
            "be imported — DSP611/DSP612/DSP613 did NOT run",
    rationale="The collective-semantics checks borrow the CommLedger's "
              "parser so the wire model has one implementation; when "
              "that import fails (broken environment, vendored tools "
              "without the profiling package) the checks silently not "
              "running would read as 'verified clean' — the exact "
              "silence this rule family exists to eliminate.",
    autofix_hint="Run the verifier in an environment where "
                 "deepspeed_tpu.profiling imports (any env that can "
                 "train), or fix the import error it reports."))

register_rule(Rule(
    id="DSO701", name="serialized-collective", severity="warning",
    summary="fully serialized collective(s) with enough independent "
            "compute available to hide them",
    rationale="A sync-form collective blocks its dependents for its "
              "full wire time even when the program holds compute that "
              "depends on neither its inputs nor its outputs — wire "
              "seconds paid as step latency that an async "
              "-start/-done schedule would hide for free.  The overlap "
              "analyzer (profiling/overlap.py) only fires this when "
              "the independent-compute window clears a floor "
              "(DSO701_MIN_WINDOW_SECONDS): micro-programs have "
              "nothing to overlap WITH.",
    autofix_hint="Let XLA's async scheduler split the op "
                 "(--xla_tpu_enable_async_collective_*), or "
                 "restructure so dependent work moves off the "
                 "collective's path; ratchet intentional cases via "
                 "`--baseline`."))

register_rule(Rule(
    id="DSO702", name="serialized-host-transfer", severity="warning",
    summary="serialized host transfer(s) adjacent to independent "
            "compute — the offload tax, statically",
    rationale="Host<->device round trips (copy-start without "
              "overlapping schedule, or the engine's DECLARED "
              "offload-state stream running between dispatches) pay "
              "full wire latency while compute that could hide them "
              "sits idle — PERF.md's ~2x offload-tax accounting, per "
              "program.  The exposed seconds this rule quotes are the "
              "exact metric the overlapped-streaming work (ROADMAP "
              "item 2) must drive down; the --baseline ratchet records "
              "today's known-serialized stream without gating it.",
    autofix_hint="Double-buffer the chunk stream (prefetch group k+1 "
                 "while group k updates, overlap write-back with the "
                 "next fetch); on TPU lowerings, move transfers to "
                 "async copy-start/copy-done pairs."))

register_rule(Rule(
    id="DSO704", name="exposed-wire-regression", severity="warning",
    summary="a program's exposed wire grew past the baseline-recorded "
            "figure — the stream is re-serializing",
    rationale="DSO702 only fires when a host stream is FULLY "
              "serialized; a change that keeps the pipelined schedule "
              "but quietly grows its exposed fraction (fewer chunks, a "
              "shrunk prefetch queue, compute moved off the hiding "
              "window) would pass it.  The baseline's recorded "
              "exposed_wire_seconds metric is the ratchet: current "
              "exposure beyond the recorded value (+tolerance) fails "
              "CI even though every node still classifies as "
              "partially overlapped.",
    autofix_hint="Restore the overlap (offload_overlap/prefetch "
                 "depth), or re-record with --update-baseline if the "
                 "growth is intended and reviewed."))

register_rule(Rule(
    id="DSO705", name="attribution-drift", severity="warning",
    summary="the reconciled step budget drifts from the "
            "baseline-recorded attribution metrics beyond tolerance",
    rationale="The attribution model's worth is that its predicted "
              "budget stays reconciled with reality: a re-analyzed "
              "predicted_step_seconds drifting from the recorded "
              "figure means the declared budget (schedule, roofline "
              "inputs, stream declaration) changed without review, "
              "and a measured run whose step_unexplained_fraction "
              "exceeds the recorded ceiling means the model no longer "
              "explains where the step goes — either way the receipts "
              "bench/multichip quote are unaudited.",
    autofix_hint="Re-reconcile (fix the declaration or the model), or "
                 "re-record with --update-baseline if the drift is "
                 "intended and reviewed."))

register_rule(Rule(
    id="DSO703", name="overlap-model-drift", severity="warning",
    summary="recorded overlap summary drifts from the HLO re-analysis "
            "beyond tolerance",
    rationale="The sidecar's recorded exposure figures are what bench "
              "receipts and the ratchet baseline quote; if re-analyzing "
              "the dumped HLO disagrees, the artifact is stale (edited, "
              "or recorded by a drifted analyzer) and the quoted "
              "exposed-wire receipts are unauditable — the DSP613 "
              "argument, applied to the exposure model.",
    autofix_hint="Re-dump the program artifacts from a fresh compile "
                 "(delete <run_dir>/programs and rerun)."))

register_rule(Rule(
    id="DSS801", name="declared-sharded-materialized-replicated",
    severity="error",
    summary="a tensor declared sharded over a mesh axis compiled with "
            "a replicated (or coarser) layout — per-device memory "
            "silently multiplies by the dropped axis product",
    rationale="Parameter sharding (ZeRO stages, tensor parallelism) is "
              "a capacity contract: the planner and the bench receipts "
              "divide state bytes by the declared axis product.  GSPMD "
              "can silently materialize a replicated layout instead (a "
              "dropped out_sharding, a constraint lost through a "
              "fusion/while body) and NOTHING fails — training is "
              "numerically identical, loss is finite, and every device "
              "pays ×dp resident bytes.  The silent dp-fold-of-memory "
              "bug stage 3 will be built against; the same silence "
              "class as the PR 8 flatten replica-sum bug.",
    autofix_hint="Pin the layout with in_shardings/out_shardings (or "
                 "lax.with_sharding_constraint inside the jit) and "
                 "re-dump; the entry parameter named in the message "
                 "shows the materialized annotation."))

register_rule(Rule(
    id="DSS802", name="unpriced-reshard", severity="warning",
    summary="a state family materializes with DIFFERENT shard layouts "
            "across programs of one run — the boundary pays an "
            "unpriced reshard",
    rationale="When the producer of a tensor family (e.g. cast_params) "
              "compiles one layout and its consumer (train_step, "
              "serve_decode) another, the runtime inserts all-to-all / "
              "collective-permute / copy traffic at the program "
              "boundary that no ledger priced — wire seconds and HBM "
              "spikes invisible to every receipt.  One layout per "
              "family per run, or an explicit reshard program that the "
              "comm ledger prices.",
    autofix_hint="Align the producer's out_shardings with the "
                 "consumer's in_shardings (the declared_sharding "
                 "sidecars name both layouts), or ratchet an "
                 "intentional boundary via --baseline."))

register_rule(Rule(
    id="DSS803", name="param-bytes-ratchet", severity="warning",
    summary="per-device parameter bytes grew past the "
            "baseline-recorded figure — sharding is regressing",
    rationale="DSS801 only fires when a DECLARED-sharded tensor "
              "materializes replicated; a change that weakens the "
              "declaration itself (or re-replicates state the baseline "
              "era had sharded) passes it.  The baseline's recorded "
              "param_bytes_per_device metric is the ratchet — the "
              "DSO704/705 mechanism applied to resident parameter "
              "memory, and the receipt half of ROADMAP item 2's "
              "planner-verified ÷dp criterion.",
    autofix_hint="Restore the sharded layout, or re-record with "
                 "--update-baseline if the growth is intended and "
                 "reviewed."))

register_rule(Rule(
    id="DSS804", name="sharding-analysis-unavailable",
    severity="warning",
    summary="the HLO sharding parser (profiling/sharding.py) could "
            "not be imported — DSS801/DSS802/DSS803 did NOT run",
    rationale="The sharding-residency checks borrow the profiling "
              "package's parser so the layout math has one "
              "implementation; when that import fails the checks "
              "silently not running would read as 'verified clean' — "
              "the DSP614 contract: UNVERIFIED, never silently clean.",
    autofix_hint="Run the verifier in an environment where "
                 "deepspeed_tpu.profiling imports (any env that can "
                 "train), or fix the import error it reports."))

register_rule(Rule(
    id="DSP613", name="comm-ledger-drift", severity="warning",
    summary="recorded CommLedger totals drift from the HLO re-parse "
            "beyond tolerance",
    rationale="The run artifact's recorded collective/wire-byte totals "
              "are what bench receipts and regression gates quote; if "
              "re-walking the dumped HLO disagrees, the artifact is "
              "stale (edited, or recorded by a drifted parser) and the "
              "quoted receipts are unauditable.",
    autofix_hint="Re-dump the program artifacts from a fresh compile "
                 "(delete <run_dir>/programs and rerun)."))


# ---------------------------------------------------------------------------
# HLO text helpers
# ---------------------------------------------------------------------------

# one module-header alias entry: ``{1}: (0, {}, may-alias)`` —
# (output tuple index path): (parameter number, param index path, kind)
_ALIAS_ENTRY_RE = re.compile(
    r"\{(?P<out>[0-9, ]*)\}\s*:\s*\((?P<param>\d+),\s*\{[0-9, ]*\},\s*"
    r"(?P<kind>may-alias|must-alias)\)")
_ALIAS_HEADER_RE = re.compile(r"input_output_alias=\{")

# scalar f32/f64 constants in optimized HLO (array literals don't match)
_CONST_RE = re.compile(r"constant\((-?[0-9][0-9.eE+-]*)\)")


def parse_input_output_aliases(hlo_text: str) -> List[Tuple[str, int]]:
    """``[(output_index_path, parameter_number)]`` from the module
    header's ``input_output_alias`` map (empty when the program
    materialized no aliases)."""
    m = _ALIAS_HEADER_RE.search(hlo_text)
    if m is None:
        return []
    # entries live between the header's braces; scanning the following
    # header line is enough (entries never span lines)
    segment = hlo_text[m.end():hlo_text.find("\n", m.end())]
    return [(e.group("out").strip(), int(e.group("param")))
            for e in _ALIAS_ENTRY_RE.finditer(segment)]


def _parse_collectives(hlo_text: str, all_participants: int):
    """The CommLedger's own parser, borrowed lazily (one wire-model
    implementation); None when unavailable (dslint running without the
    package's profiling modules)."""
    try:
        from ...profiling import comm as comm_prof
    except Exception:
        return None
    return comm_prof.parse_hlo_collectives(
        hlo_text, all_participants=all_participants)


def _collective_summary(ops):
    try:
        from ...profiling import comm as comm_prof
    except Exception:
        return None
    return comm_prof.collective_summary(ops)


def has_mean_scaling_evidence(hlo_text: str, group: int) -> bool:
    """Whether the module holds a scaling constant consistent with a
    mean over a ``group``-wide replica group: any fractional constant
    ``c`` with ``1/c`` an integer that ``group`` divides.  Covers both
    the direct pmean lowering (``multiply(all-reduce, 1/g)``) and a
    loss normalized by the global element count (``1/(g·k)``)."""
    if group <= 1:
        return True
    for tok in set(_CONST_RE.findall(hlo_text)):
        try:
            c = float(tok)
        except ValueError:
            continue
        if not 0.0 < abs(c) < 1.0:
            continue
        inv = 1.0 / abs(c)
        k = round(inv)
        if k and abs(inv - k) <= 1e-6 * inv and k % group == 0:
            return True
    return False


# ---------------------------------------------------------------------------
# Program artifacts
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProgramArtifact:
    """One compiled program plus the metadata the DSP6xx rules need.

    Built live from an engine's ledger (``profiling/verify.py``) or
    loaded from a ``<run_dir>/programs/`` dump.  ``path`` is what
    diagnostics point at (the ``.hlo`` file, or a ``<program>`` pseudo
    path for in-memory verification)."""

    name: str
    hlo: str
    path: str = ""
    # declared pytree-level donate_argnums (empty tuple/None = no
    # donation declared; the DSP60x checks then have nothing to verify)
    donate_argnums: Optional[Tuple[int, ...]] = None
    # memory_analysis alias bytes (None = analysis unavailable)
    alias_size_in_bytes: Optional[int] = None
    mesh_axes: Dict[str, int] = dataclasses.field(default_factory=dict)
    data_axis: str = "data"
    # total bytes of the flat parameter master (the DSP611 payload
    # floor); None disables the parameter-shape test
    param_bytes: Optional[int] = None
    # the CommLedger entry recorded at compile time (DSP613 cross-check;
    # its "overlap" sub-dict is the DSO703 cross-check)
    comm: Optional[dict] = None
    # init-provenance note from the flat coordinator (informational)
    master_provenance: Optional[str] = None
    # engine-declared per-step host-state stream bytes (the offload
    # round trips that run BETWEEN dispatches, invisible in this
    # program's HLO) — producers set it only on update programs
    host_state_wire_bytes: Optional[int] = None
    # the declared ISSUE SCHEDULE of that stream ({overlap,
    # prefetch_depth, chunks, groups, form, ...}): how the engine
    # actually sequences the chunk transfers — what the overlap
    # analyzer prices exposure from (None = serialized by construction)
    host_stream_schedule: Optional[dict] = None
    # the declared bucketed-collective schedule (overlap_comm bucket
    # geometry, {overlap, rs_buckets, ag_buckets, ...}) of the ZeRO-2
    # gradient exchange — producers set it only on exchange programs;
    # None = no bucketed exchange declared (no claim either way)
    collective_schedule: Optional[dict] = None
    # device_kind string the roofline/wire tables resolve against
    device_kind: Optional[str] = None
    # the engine-DECLARED sharding spec ({tag, mesh_axes, families:
    # {name: {leaves: [{bytes, axes, divisor}], total_bytes}}}), built
    # from the same mesh/PartitionSpec tuples the jits were given —
    # what the DSS8xx sharding auditor reconciles the materialized HLO
    # layouts against; None = nothing declared (no claim either way)
    declared_sharding: Optional[dict] = None

    def __post_init__(self):
        if not self.path:
            self.path = f"<{self.name}>"
        if self.donate_argnums is not None:
            self.donate_argnums = tuple(int(i) for i in self.donate_argnums)

    @property
    def total_devices(self) -> int:
        n = 1
        for size in self.mesh_axes.values():
            n *= int(size)
        return n

    def sidecar(self) -> dict:
        """The JSON sidecar ``profiling/verify.ProgramDumper`` writes."""
        return {
            "artifact_schema_version": ARTIFACT_SCHEMA_VERSION,
            "program": self.name,
            "hlo_file": f"{self.name}.hlo",
            "donate_argnums": (list(self.donate_argnums)
                               if self.donate_argnums is not None else None),
            "alias_size_in_bytes": self.alias_size_in_bytes,
            "mesh_axes": dict(self.mesh_axes),
            "data_axis": self.data_axis,
            "param_bytes": self.param_bytes,
            "comm": self.comm,
            "master_provenance": self.master_provenance,
            "host_state_wire_bytes": self.host_state_wire_bytes,
            "host_stream_schedule": self.host_stream_schedule,
            "collective_schedule": self.collective_schedule,
            "device_kind": self.device_kind,
            "declared_sharding": self.declared_sharding,
        }


def _load_declared_sharding(side: dict) -> Optional[dict]:
    """Type-validated ``declared_sharding`` from one sidecar dict.
    Raises ``TypeError``/``ValueError`` (→ the CLI's malformed-sidecar
    exit-2 contract) when the field is present but not the declared
    shape — a tampered sidecar must fail loudly, not quietly disable
    the DSS8xx reconciliation."""
    declared = side.get("declared_sharding")
    if declared is None:
        return None
    if not isinstance(declared, dict):
        raise TypeError(
            f"declared_sharding must be an object, got "
            f"{type(declared).__name__}")
    families = declared.get("families")
    if families is not None and not isinstance(families, dict):
        raise TypeError(
            f"declared_sharding.families must be an object, got "
            f"{type(families).__name__}")
    for fam, spec in (families or {}).items():
        if not isinstance(spec, dict) \
                or not isinstance(spec.get("leaves", []), list):
            raise TypeError(
                f"declared_sharding.families[{fam!r}] must be an "
                "object with a 'leaves' list")
    return dict(declared)


def load_run_artifacts(run_dir: str) -> List[ProgramArtifact]:
    """Artifacts from ``<run_dir>/programs/*.json`` (+ their ``.hlo``
    texts).  Accepts the programs dir itself too.  Raises
    ``FileNotFoundError`` when neither exists."""
    progdir = os.path.join(run_dir, PROGRAMS_DIRNAME)
    if not os.path.isdir(progdir):
        if os.path.isdir(run_dir) and any(
                n.endswith(".json") for n in os.listdir(run_dir)):
            progdir = run_dir
        else:
            raise FileNotFoundError(
                f"no program artifacts under {run_dir!r} (expected "
                f"{PROGRAMS_DIRNAME}/<name>.json sidecars — run with "
                "profiling.program_dump enabled)")
    out = []
    for name in sorted(os.listdir(progdir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(progdir, name)
        with open(path, "r", encoding="utf-8") as f:
            side = json.load(f)
        if not isinstance(side, dict) or "program" not in side:
            continue  # foreign json in a shared dir
        hlo_name = side.get("hlo_file") or f"{side['program']}.hlo"
        if not isinstance(hlo_name, str):
            raise ValueError(
                f"malformed program sidecar {path}: hlo_file must be a "
                f"string, got {type(hlo_name).__name__}")
        hlo_path = os.path.join(progdir, hlo_name)
        try:
            with open(hlo_path, "r", encoding="utf-8") as f:
                hlo = f.read()
        except OSError:
            hlo = ""
        try:
            out.append(ProgramArtifact(
                name=str(side["program"]), hlo=hlo, path=hlo_path,
                donate_argnums=(tuple(side["donate_argnums"])
                                if side.get("donate_argnums") else None),
                alias_size_in_bytes=side.get("alias_size_in_bytes"),
                mesh_axes=dict(side.get("mesh_axes") or {}),
                data_axis=side.get("data_axis") or "data",
                param_bytes=side.get("param_bytes"),
                comm=side.get("comm"),
                master_provenance=side.get("master_provenance"),
                host_state_wire_bytes=(
                    int(side["host_state_wire_bytes"])
                    if side.get("host_state_wire_bytes") is not None
                    else None),
                host_stream_schedule=(
                    dict(side["host_stream_schedule"])
                    if isinstance(side.get("host_stream_schedule"), dict)
                    else None),
                collective_schedule=(
                    dict(side["collective_schedule"])
                    if isinstance(side.get("collective_schedule"), dict)
                    else None),
                device_kind=side.get("device_kind"),
                declared_sharding=_load_declared_sharding(side)))
        except (TypeError, ValueError) as e:
            # type-malformed sidecar (donate_argnums: 5, mesh_axes as a
            # list, ...): a usage-class load failure the CLI reports as
            # exit 2, never a traceback
            raise ValueError(
                f"malformed program sidecar {path}: {e}") from e
    if not out:
        # a run dir full of OTHER json (latency-rank*.json etc.) must
        # not read as "0 programs, verified clean" — a run that never
        # dumped (program_dump off) fails the CI verify step loudly
        raise FileNotFoundError(
            f"no program artifacts under {run_dir!r} (found json files "
            f"but none with a 'program' sidecar key — was "
            "profiling.program_dump enabled for this run?)")
    return out


# ---------------------------------------------------------------------------
# HLO-side verification passes
# ---------------------------------------------------------------------------

def _pdiag(artifact, rule_id, message) -> Diagnostic:
    return Diagnostic(path=artifact.path, line=1, col=1, rule_id=rule_id,
                      message=f"[{artifact.name}] {message}")


def check_donation(artifact: ProgramArtifact) -> List[Diagnostic]:
    """DSP601/DSP602: declared donation must materialize as
    input→output aliases in the compiled module."""
    declared = artifact.donate_argnums
    if not declared or not artifact.hlo:
        return []
    aliases = parse_input_output_aliases(artifact.hlo)
    if not aliases:
        return [_pdiag(
            artifact, "DSP601",
            f"donate_argnums={tuple(declared)} declared but the compiled "
            "module header carries NO input_output_alias entries — every "
            "donated state buffer is copied, not reused")]
    # Partial-drop lower bound: each donated pytree argument flattens
    # to >= 1 HLO parameter, so fewer DISTINCT aliased parameters than
    # declared argnums proves at least one donated argument aliased
    # nothing at all.  This is a lower bound only — per-ARGUMENT
    # coverage needs the pytree->parameter mapping, which the artifact
    # does not carry, so a dropped buffer inside a multi-leaf argument
    # (XLA's "Some donated buffers were not usable" warning) can still
    # pass; the verdict is program-granular by design.
    aliased_params = {param for _, param in aliases}
    if len(aliased_params) < len(declared):
        return [_pdiag(
            artifact, "DSP602",
            f"only {len(aliased_params)} distinct aliased parameter(s) "
            f"for {len(declared)} donated argument(s) "
            f"(donate_argnums={tuple(declared)}): at least one donated "
            "argument materialized no alias — its buffers are copied, "
            "not reused, and the capacity math overcounts")]
    if artifact.alias_size_in_bytes == 0 \
            or artifact.alias_size_in_bytes is None:
        # byte accounting unverifiable either way — explicit downgraded
        # verdict, never silence: 0 is the documented warm-cache
        # deserialization caveat, None means the backend (or sidecar)
        # carried no memory_analysis at all
        why = ("memory_analysis reports alias=0 bytes "
               "(cache-deserialized executable)"
               if artifact.alias_size_in_bytes == 0 else
               "no memory_analysis byte data available for this "
               "executable")
        return [_pdiag(
            artifact, "DSP602",
            f"{len(aliases)} input_output_alias entr"
            f"{'y' if len(aliases) == 1 else 'ies'} verified from HLO "
            f"text, but {why}; byte accounting unverifiable, predicted "
            "peaks conservative")]
    return []


def check_collectives(artifact: ProgramArtifact) -> List[Diagnostic]:
    """DSP611/DSP612/DSP613 over one program's optimized HLO."""
    if not artifact.hlo:
        return []
    ops = _parse_collectives(artifact.hlo, artifact.total_devices)
    if ops is None:
        # parser unavailable: the checks did NOT run — say so loudly
        # instead of reading as verified-clean (DSP614)
        return [_pdiag(
            artifact, "DSP614",
            "collective parser (deepspeed_tpu.profiling.comm) "
            "unimportable in this environment — DSP611/DSP612/DSP613 "
            "were skipped, this program's collective semantics are "
            "UNVERIFIED")]
    out: List[Diagnostic] = []
    dp = max(int(artifact.mesh_axes.get(artifact.data_axis, 1)), 1)

    # DSP611: parameter-sized all-reduce spanning a non-data axis.
    # Exemption (see the rule rationale): a program that ALSO holds
    # collectives of any other shape — data-axis-scoped reductions,
    # gathers, scatters — is a step program whose full-mesh sum is a
    # GSPMD assembly over partition-exact DUS writes (parity-exact by
    # measurement); only the init/flatten signature, where the suspect
    # sum is the sole collective shape, fires.
    if artifact.param_bytes:
        suspects = [rec for rec in ops
                    if rec["op"] == "all-reduce" and rec["group"] > dp
                    and rec["out_bytes"] >= artifact.param_bytes]
        assembly_evidence = any(
            rec["op"] != "all-reduce" or rec["group"] <= dp
            for rec in ops if rec not in suspects)
        for rec in () if assembly_evidence else suspects:
            factor = rec["group"] // dp
            out.append(_pdiag(
                artifact, "DSP611",
                f"all-reduce over {rec['group']} devices sums a "
                f"parameter-sized tensor ({rec['out_bytes']} bytes >= "
                f"flat master {artifact.param_bytes}) but the "
                f"{artifact.data_axis} axis is only {dp} wide: the "
                f"non-data replicas get SUMMED and every parameter "
                f"arrives ×{factor} (the flatten-×tp bug shape)"))

    # DSP612: scalar psum with no mean-compensation constant in sight
    for rec in ops:
        if (rec["op"] == "all-reduce" and rec["group"] > 1
                and rec["out_bytes"] <= 8
                and not has_mean_scaling_evidence(artifact.hlo,
                                                 rec["group"])):
            out.append(_pdiag(
                artifact, "DSP612",
                f"scalar all-reduce over {rec['group']} replicas with no "
                f"1/k scaling constant (k divisible by {rec['group']}) "
                "anywhere in the module — psum where the step semantics "
                "likely require a mean"))

    # DSP613: recorded ledger entry vs re-parse
    if artifact.comm:
        fresh = _collective_summary(ops)
        if fresh is not None:
            drifts = []
            if fresh["collectives"] != artifact.comm.get("collectives"):
                drifts.append(
                    f"collectives {artifact.comm.get('collectives')} -> "
                    f"{fresh['collectives']}")
            for field in ("payload_bytes", "wire_bytes"):
                rec_v = artifact.comm.get(field)
                new_v = fresh[field]
                if rec_v is None:
                    continue
                tol = max(abs(new_v), 1) * 0.02
                if abs(int(rec_v) - int(new_v)) > tol:
                    drifts.append(f"{field} {rec_v} -> {new_v}")
            if drifts:
                out.append(_pdiag(
                    artifact, "DSP613",
                    "recorded comm-ledger totals drift from the HLO "
                    f"re-parse: {'; '.join(drifts)} (stale or tampered "
                    "artifact)"))
    return out


# ---------------------------------------------------------------------------
# DSS8xx: sharding residency audit (profiling/sharding.py)
# ---------------------------------------------------------------------------

# DSS801 fires only on tensors at least this large: CI fixtures are
# MiB-scale, and a sub-MiB fold cannot meaningfully move capacity
SHARDING_MIN_TENSOR_BYTES = 1 << 20

# relative growth of param_bytes_per_device beyond the recorded metric
# that trips DSS803 (byte counts are exact per geometry; the tolerance
# absorbs dtype/padding drift of a reviewed model resize, nothing more)
PARAM_BYTES_RATCHET_TOL = 0.10


def _load_sharding():
    """The profiling package's sharding parser, borrowed lazily (one
    layout-math implementation); None when unavailable — the DSS804
    loud-failure path."""
    try:
        from ...profiling import sharding as sharding_prof
    except Exception:
        return None
    return sharding_prof


def program_sharding(artifact: ProgramArtifact):
    """The sharding residency summary (profiling/sharding.py) of one
    artifact — declared-vs-materialized reconciliation included when
    the artifact carries a declared spec — memoized on the artifact;
    None when the parser is unavailable or the text holds no
    computation."""
    if "_sharding_summary" not in artifact.__dict__:
        summary = None
        mod = _load_sharding()
        if mod is not None and artifact.hlo:
            try:
                summary = mod.analyze_sharding(
                    artifact.hlo, declared=artifact.declared_sharding)
            except Exception:
                summary = None
        artifact.__dict__["_sharding_summary"] = summary
    return artifact.__dict__["_sharding_summary"]


def check_sharding(artifact: ProgramArtifact) -> List[Diagnostic]:
    """DSS801/DSS804 over one program: every declared-sharded tensor
    must materialize its divisor in the compiled layout."""
    if not artifact.hlo or artifact.declared_sharding is None:
        # nothing declared: no claim either way (pre-DSS8 sidecars
        # stay clean; engines always declare from this round on)
        return []
    if _load_sharding() is None:
        return [_pdiag(
            artifact, "DSS804",
            "sharding parser (deepspeed_tpu.profiling.sharding) "
            "unimportable in this environment — DSS801/DSS802/DSS803 "
            "were skipped, this program's parameter residency is "
            "UNVERIFIED")]
    summary = program_sharding(artifact)
    if summary is None:
        return []
    out: List[Diagnostic] = []
    for fam in sorted(summary["families"]):
        for mm in summary["families"][fam]["mismatches"]:
            if mm["bytes"] < SHARDING_MIN_TENSOR_BYTES:
                continue
            ddiv = mm["declared_divisor"]
            mdiv = max(mm["materialized_divisor"], 1)
            fold = ddiv // mdiv
            axes = "/".join(mm["axes"]) or "?"
            out.append(_pdiag(
                artifact, "DSS801",
                f"{fam} tensor ({mm['bytes']} bytes) declared sharded "
                f"over axis '{axes}' (÷{ddiv}) but materialized "
                f"{'replicated' if mdiv == 1 else f'÷{mdiv}'}: "
                f"per-device resident bytes ×{fold} "
                f"({mm['bytes'] // ddiv} declared -> "
                f"{mm['bytes'] // mdiv} actual bytes/device) — the "
                "silent dp-fold-of-memory shape (pin the layout with "
                "out_shardings/with_sharding_constraint)"))
    return out


def check_sharding_consistency(artifacts) -> List[Diagnostic]:
    """DSS802 across the programs of one run: a state family that
    materializes with different shard divisors in two programs pays an
    unpriced reshard at the boundary.  Reference layout per family =
    the program carrying the most matched bytes (names break ties);
    every disagreeing program gets one finding."""
    placements = {}  # family -> [(artifact, divisor, matched_bytes)]
    for artifact in artifacts:
        if artifact.declared_sharding is None:
            continue
        summary = program_sharding(artifact)
        if summary is None:
            continue
        for fam, info in summary["families"].items():
            if info["materialized_divisor"] is None:
                continue
            placements.setdefault(fam, []).append(
                (artifact, info["materialized_divisor"],
                 info["matched_bytes"]))
    out: List[Diagnostic] = []
    for fam in sorted(placements):
        entries = placements[fam]
        if len({div for _, div, _ in entries}) <= 1:
            continue
        ref_artifact, ref_div, _ = max(
            entries, key=lambda e: (e[2], e[0].name))
        for artifact, div, _ in sorted(entries, key=lambda e: e[0].name):
            if div == ref_div:
                continue
            resharded = _load_sharding()
            n_reshard = (resharded.count_reshard_ops(artifact.hlo)
                         if resharded is not None else 0)
            out.append(_pdiag(
                artifact, "DSS802",
                f"family '{fam}' materializes ÷{div} here but ÷"
                f"{ref_div} in [{ref_artifact.name}]: the program "
                "boundary pays an unpriced reshard (producer/consumer "
                f"layout mismatch; {n_reshard} all-to-all/"
                "collective-permute op(s) in this module) — align the "
                "out_shardings with the consumer or price an explicit "
                "reshard program"))
    return out


def sharding_metric_key(tag: str, name: str) -> str:
    """Baseline ``metrics`` key for one program's per-device parameter
    bytes.  TAG-qualified (unlike the exposure keys): the canonical CI
    fixtures (zero2-overlap dp4, offload dp1) share program names AND
    model geometry, and both must ratchet independently."""
    return f"<programs>|param_bytes_per_device|{tag}|{name}"


def _sharding_tag(artifact):
    tag = (artifact.declared_sharding or {}).get("tag")
    return str(tag) if tag else None


def sharding_metrics(artifacts) -> dict:
    """``{metric key: param_bytes_per_device}`` for every artifact
    whose params family matched the compiled layout — what
    ``--update-baseline`` records so DSS803 can ratchet resident
    parameter memory (the receipt half of ROADMAP item 2's ÷dp
    criterion)."""
    out = {}
    for artifact in artifacts:
        tag = _sharding_tag(artifact)
        if tag is None:
            continue
        summary = program_sharding(artifact)
        if summary is None or summary["param_bytes_per_device"] is None:
            continue
        out[sharding_metric_key(tag, artifact.name)] = float(
            summary["param_bytes_per_device"])
    return out


def check_sharding_ratchet(artifacts, baseline_metrics) -> List[Diagnostic]:
    """DSS803: programs whose re-analyzed per-device parameter bytes
    exceed the baseline-recorded figure by more than the tolerance.
    Programs without a recorded metric are not checked — the ratchet
    only ever tightens what a reviewer recorded."""
    out: List[Diagnostic] = []
    if not baseline_metrics:
        return out
    for artifact in artifacts:
        tag = _sharding_tag(artifact)
        if tag is None:
            continue
        recorded = baseline_metrics.get(
            sharding_metric_key(tag, artifact.name))
        if recorded is None:
            continue
        summary = program_sharding(artifact)
        if summary is None or summary["param_bytes_per_device"] is None:
            continue
        current = float(summary["param_bytes_per_device"])
        ceiling = float(recorded) * (1.0 + PARAM_BYTES_RATCHET_TOL)
        if current > ceiling:
            out.append(_pdiag(
                artifact, "DSS803",
                f"param_bytes_per_device grew {float(recorded):.0f} -> "
                f"{current:.0f} (+{PARAM_BYTES_RATCHET_TOL:.0%} "
                "tolerance exceeded): resident parameter memory is "
                "regressing (weakened sharding or re-replicated "
                "state) — restore the layout or re-record with "
                "--update-baseline"))
    return out


def program_overlap(artifact: ProgramArtifact):
    """The overlap/critical-path analysis (profiling/overlap.py) for
    one artifact, memoized on the artifact; None when the analyzer is
    unavailable or the text holds no computation."""
    if "_overlap_summary" not in artifact.__dict__:
        summary = None
        try:
            from ...profiling import overlap as overlap_prof

            # max_nodes=None: the rule checks must see EVERY node — a
            # collective-heavy program truncated at the telemetry cap
            # would silently drop the declared host-stream node (it is
            # appended last) and every finding past the cap
            summary = overlap_prof.analyze_hlo(
                artifact.hlo,
                total_devices=artifact.total_devices,
                device_kind=artifact.device_kind or "",
                declared_host_wire_bytes=(
                    artifact.host_state_wire_bytes or 0),
                declared_host_stream=artifact.host_stream_schedule,
                declared_collective_schedule=artifact.collective_schedule,
                max_nodes=None)
        except Exception:
            summary = None
        artifact.__dict__["_overlap_summary"] = summary
    return artifact.__dict__["_overlap_summary"]


# relative growth of a program's exposed_wire_seconds beyond its
# baseline-recorded metric that trips DSO704 (generous: the figure is
# model-derived and roofline-table sensitive, same rationale as the
# bench_diff exposed_wire_seconds gate)
EXPOSED_WIRE_RATCHET_TOL = 0.25
# absolute floor on the ratchet ceiling: a recorded metric at (or
# rounding to) 0.0 must not make every epsilon of cost-model noise a
# CI failure — 10 µs of exposure is below anything worth gating
EXPOSED_WIRE_RATCHET_EPS = 1e-5


def exposure_metric_key(name: str) -> str:
    """Baseline ``metrics`` key for one program's exposed wire."""
    return f"<programs>|exposed_wire_seconds|{name}"


def comm_exposure_metric_key(name: str, tag=None) -> str:
    """Baseline ``metrics`` key for one program's exposed COLLECTIVE
    wire under a declared overlap_comm schedule.  A distinct metric
    name, not a reuse of :func:`exposure_metric_key`: the checked-in
    baseline records the offload fixture's host-stream exposure and the
    zero-2 fixture's collective exposure for programs that share the
    ``train_step`` name — one key would collide across the two
    recorded run dirs.  TAG-qualified when the artifact declares a
    sharding tag (round 20: the zero-2-overlap AND stage-3 fixtures
    both dump an overlapped ``train_step`` with the same model
    geometry — a name-only key would be last-write-wins across the
    recorded run dirs, corrupting whichever fixture regenerated
    first); ``tag=None`` keeps the legacy name-only form for
    artifacts without a declared sharding."""
    if tag:
        return f"<programs>|comm_exposed_wire_seconds|{tag}|{name}"
    return f"<programs>|comm_exposed_wire_seconds|{name}"


def _exposure_keys(artifact):
    """The baseline metric keys this artifact ratchets under: the
    host-stream key when it declares an offload stream, the
    collective key when it declares an OVERLAPPED bucketed exchange
    (a serialized control must not record/ratchet its own exposure —
    it exists to be worse)."""
    keys = []
    if artifact.host_state_wire_bytes:
        keys.append(exposure_metric_key(artifact.name))
    if (artifact.collective_schedule or {}).get("overlap"):
        keys.append(comm_exposure_metric_key(artifact.name,
                                             _sharding_tag(artifact)))
    return keys


def exposure_metrics(artifacts) -> dict:
    """``{metric key: exposed_wire_seconds}`` for every artifact that
    declares a host stream or an overlapped collective schedule — what
    ``--update-baseline`` records so a later run can ratchet against
    it (``check_exposure_ratchet``)."""
    out = {}
    for artifact in artifacts:
        keys = _exposure_keys(artifact)
        if not keys:
            continue
        summary = program_overlap(artifact)
        if summary is None:
            continue
        for key in keys:
            out[key] = round(float(summary["exposed_wire_seconds"]), 9)
    return out


def check_exposure_ratchet(artifacts, baseline_metrics) -> List[Diagnostic]:
    """DSO704: programs whose re-analyzed exposed wire exceeds the
    baseline-recorded metric by more than the tolerance.  Programs
    without a recorded metric are not checked (the ratchet only ever
    tightens what a reviewer recorded)."""
    out: List[Diagnostic] = []
    if not baseline_metrics:
        return out
    for artifact in artifacts:
        recorded = None
        for key in _exposure_keys(artifact):
            if baseline_metrics.get(key) is not None:
                recorded = baseline_metrics[key]
                break
        if recorded is None:
            continue
        summary = program_overlap(artifact)
        if summary is None:
            continue
        current = float(summary["exposed_wire_seconds"])
        ceiling = (float(recorded) * (1.0 + EXPOSED_WIRE_RATCHET_TOL)
                   + EXPOSED_WIRE_RATCHET_EPS)
        if current > ceiling:
            out.append(_pdiag(
                artifact, "DSO704",
                f"exposed_wire_seconds grew {float(recorded):.6f} -> "
                f"{current:.6f} (+{EXPOSED_WIRE_RATCHET_TOL:.0%} "
                "tolerance exceeded): the stream/exchange is "
                "re-serializing — restore the overlapped schedule or "
                "re-record with --update-baseline"))
    return out


# two-sided drift band on the re-analyzed predicted_step_seconds vs the
# baseline-recorded figure (model-derived and deterministic per
# toolchain, so a generous band only catches real declaration drift)
PREDICTED_STEP_RATCHET_TOL = 0.25
PREDICTED_STEP_RATCHET_EPS = 1e-5
# absolute headroom over the recorded step_unexplained_fraction ceiling
# (the fraction is measured-latency-derived, hence noisy)
UNEXPLAINED_RATCHET_MARGIN = 0.05


def predicted_step_metric_key(name: str) -> str:
    """Baseline ``metrics`` key for one program's predicted step
    seconds (the attribution budget's deterministic half)."""
    return f"<programs>|predicted_step_seconds|{name}"


def unexplained_metric_key(name: str) -> str:
    """Baseline ``metrics`` key for one program's reconciled
    unexplained-fraction ceiling (the measured half; recorded only
    when the run dir carries latency evidence)."""
    return f"<programs>|step_unexplained_fraction|{name}"


def program_attribution(artifact: ProgramArtifact):
    """The attribution phase budget (profiling/attribution) of one
    artifact's re-analyzed overlap summary; None when the analyzer is
    unavailable or the text holds no computation."""
    summary = program_overlap(artifact)
    if summary is None:
        return None
    try:
        from ...profiling import attribution as attr_prof
    except Exception:
        return None
    return attr_prof.program_budget(summary)


def _run_dir_measured_p50(run_dir):
    """Fleet-median measured p50 seconds from a run dir's
    ``latency-rank*.json`` skew-exchange files (the offline CLI's
    measured evidence); None when the dir holds none or the profiling
    package is unavailable."""
    if not run_dir:
        return None
    try:
        from ...profiling import attribution as attr_prof
        from ...profiling import comm as comm_prof
    except Exception:
        return None
    # relative staleness guard: an elastic run leaves dead ranks' last
    # publishes behind, and offline analysis cannot use wall-clock age
    fleet = attr_prof.fresh_fleet_snapshots(
        comm_prof.read_fleet_latencies(str(run_dir)))
    vals = [float(snap["p50"]) for snap in fleet.values()
            if snap.get("p50") and float(snap["p50"]) > 0]
    return attr_prof.median_of_window(vals, window=max(len(vals), 1))


def attribution_metrics(artifacts, run_dir=None) -> dict:
    """Attribution metric entries for ``--update-baseline``: per
    host-stream-declaring program (the same gating as
    :func:`exposure_metrics` — the offload step is the canonical CI
    anchor), the re-analyzed ``predicted_step_seconds`` and — when the
    run dir carries measured latency — the reconciled
    ``step_unexplained_fraction`` as the recorded ceiling.

    Metric keys are PROGRAM-NAME-scoped (the DSO704 exposure-metric
    convention): recording over multiple ``--programs`` dirs that dump
    the same program name collapses to one figure (last dir wins).
    The checked-in baseline anchors exactly one run dir; keep it that
    way, or name programs distinctly across dirs."""
    out = {}
    measured = _run_dir_measured_p50(run_dir)
    for artifact in artifacts:
        if not artifact.host_state_wire_bytes:
            continue
        budget = program_attribution(artifact)
        if budget is None:
            continue
        predicted = float(budget["predicted_seconds"])
        out[predicted_step_metric_key(artifact.name)] = round(predicted, 9)
        if measured and measured > 0:
            out[unexplained_metric_key(artifact.name)] = round(
                (measured - predicted) / measured, 6)
    return out


def check_attribution_ratchet(artifacts_by_dir,
                              baseline_metrics) -> List[Diagnostic]:
    """DSO705 over ``[(run_dir, artifacts)]``: programs whose
    re-analyzed predicted step drifts beyond the two-sided band around
    the recorded figure, or whose reconciled unexplained fraction (when
    the run dir carries measured latency) exceeds the recorded ceiling
    plus margin.  Programs without a recorded metric are not checked —
    the ratchet only ever tightens what a reviewer recorded."""
    out: List[Diagnostic] = []
    if not baseline_metrics:
        return out
    for run_dir, artifacts in artifacts_by_dir:
        measured = None
        measured_resolved = False
        for artifact in artifacts:
            if not artifact.host_state_wire_bytes:
                # the attribution metrics are recorded ONLY for
                # host-stream-declaring programs (attribution_metrics'
                # gate); a same-NAMED program from another fixture dir
                # (the zero-2 overlap fixture's train_step vs the
                # offload fixture's) must not ratchet against it
                continue
            rec_pred = baseline_metrics.get(
                predicted_step_metric_key(artifact.name))
            rec_ceil = baseline_metrics.get(
                unexplained_metric_key(artifact.name))
            if rec_pred is None and rec_ceil is None:
                continue
            budget = program_attribution(artifact)
            if budget is None:
                continue
            predicted = float(budget["predicted_seconds"])
            if rec_pred is not None:
                band = (abs(float(rec_pred)) * PREDICTED_STEP_RATCHET_TOL
                        + PREDICTED_STEP_RATCHET_EPS)
                if abs(predicted - float(rec_pred)) > band:
                    out.append(_pdiag(
                        artifact, "DSO705",
                        f"predicted_step_seconds drifted "
                        f"{float(rec_pred):.6f} -> {predicted:.6f} "
                        f"(±{PREDICTED_STEP_RATCHET_TOL:.0%} band "
                        "exceeded): the declared budget changed — "
                        "re-reconcile or re-record with "
                        "--update-baseline"))
            if rec_ceil is None:
                continue
            if not measured_resolved:
                measured = _run_dir_measured_p50(run_dir)
                measured_resolved = True
            if not measured:
                continue
            fraction = (measured - predicted) / measured
            if fraction > float(rec_ceil) + UNEXPLAINED_RATCHET_MARGIN:
                out.append(_pdiag(
                    artifact, "DSO705",
                    f"step_unexplained_fraction {fraction:.4f} exceeds "
                    f"the recorded ceiling {float(rec_ceil):.4f} "
                    f"(+{UNEXPLAINED_RATCHET_MARGIN} margin): the "
                    "budget no longer explains the measured step — "
                    "re-reconcile or re-record with --update-baseline"))
    return out


def check_overlap(artifact: ProgramArtifact) -> List[Diagnostic]:
    """DSO701/DSO702/DSO703 over one program's overlap analysis.

    One finding per (rule, program), aggregating every offending node:
    the ratchet baseline keys on (rule, program), so per-node findings
    would break the baseline count on any re-dump that re-splits the
    stream."""
    if not artifact.hlo:
        return []
    try:
        from ...profiling.overlap import (DSO701_MIN_WINDOW_SECONDS,
                                          KIND_COLLECTIVE, KIND_HOST,
                                          MAX_WINDOW_INSTRUCTIONS,
                                          SERIALIZED)
    except Exception:
        # the profiling package is unimportable — check_collectives'
        # DSP614 already says every HLO-side heuristic was skipped; a
        # second flag would be noise
        return []
    summary = program_overlap(artifact)
    if summary is None:
        # header-only artifact (no computation body): nothing is
        # scheduled, so there is no overlap to verify — same silence as
        # an empty collective walk
        return []
    out: List[Diagnostic] = []

    nodes = summary.get("nodes") or []
    # Window analysis degrades to None past MAX_WINDOW_INSTRUCTIONS —
    # exactly the production-size programs the analyzer targets.  The
    # window-gated checks below then never fire, and silence would
    # read as overlap-clean: say so loudly instead (the DSP614
    # contract).  Declared-stream nodes carry an explicit window and
    # are unaffected.
    unknown = [n for n in nodes
               if n["classification"] == SERIALIZED and n["seconds"] > 0
               and n.get("window_seconds") is None]
    if unknown:
        out.append(_pdiag(
            artifact, "DSP614",
            f"{len(unknown)} serialized wire node(s) have UNKNOWN "
            "independent-compute windows (program exceeds the "
            f"{MAX_WINDOW_INSTRUCTIONS}-instruction window-analysis "
            "cap) — the DSO701/DSO702 window checks did NOT run for "
            "them; their exposure is UNVERIFIED, not clean"))
    # DSO701: serialized collectives with a real window to hide them.
    # Two windows count: the DAG-independence window (floored at
    # DSO701_MIN_WINDOW_SECONDS — micro-programs have nothing to
    # overlap with), and the DECLARED potential window on nodes covered
    # by an overlap_comm collective schedule with overlap off
    # (source "hlo+declared"): there the ENGINE declared a bucketed
    # schedule exists that would free the window, so any nonzero
    # potential fires — the serialized control's receipt.
    declared_off = (artifact.collective_schedule is not None
                    and not artifact.collective_schedule.get("overlap"))
    declared_on = (artifact.collective_schedule is not None
                   and bool(artifact.collective_schedule.get("overlap")))

    def _fires(n):
        if n.get("source") == "hlo+declared":
            # scheduled exchange nodes: under an OVERLAPPED schedule
            # the residual exposure is the priced fill/drain — the
            # DSO704 exposure ratchet owns it, not DSO701; under the
            # serialized control ANY declared potential window fires
            # (the engine itself declared bucketing would free it)
            if declared_on:
                return False
            return (declared_off
                    and (n.get("window_seconds") or 0.0) > 0)
        return ((n.get("window_seconds") or 0.0)
                >= DSO701_MIN_WINDOW_SECONDS)

    culprits = [n for n in nodes
                if n["kind"] == KIND_COLLECTIVE
                and n["classification"] == SERIALIZED
                and n["seconds"] > 0 and _fires(n)]
    if culprits:
        wire_ms = sum(n["seconds"] for n in culprits) * 1e3
        window_ms = max(n["window_seconds"] for n in culprits) * 1e3
        declared = any(n.get("source") == "hlo+declared"
                       for n in culprits)
        hint = (" — overlap_comm would bucket and hide this exchange"
                if declared else
                " (no -start/-done overlap materialized)")
        out.append(_pdiag(
            artifact, "DSO701",
            f"{len(culprits)} fully serialized collective(s) paying "
            f"{wire_ms:.3f} ms of exposed wire with up to "
            f"{window_ms:.3f} ms of independent compute available to "
            f"hide them{hint}"))
    # DSO702: serialized host transfers next to independent compute
    host = [n for n in nodes
            if n["kind"] == KIND_HOST
            and n["classification"] == SERIALIZED
            and n["seconds"] > 0
            and (n.get("window_seconds") or 0.0) > 0]
    if host:
        total_bytes = sum(n["wire_bytes"] for n in host)
        exposed_ms = sum(n["seconds"] - n["hidden_seconds"]
                         for n in host) * 1e3
        sources = sorted({n["source"] for n in host})
        out.append(_pdiag(
            artifact, "DSO702",
            f"{len(host)} serialized host transfer(s) ({total_bytes} "
            f"bytes, {exposed_ms:.3f} ms exposed wire; source: "
            f"{'/'.join(sources)}) adjacent to an independent compute "
            "region — the offload tax, statically (exposed_wire_"
            f"seconds={summary['exposed_wire_seconds']:.6f})"))
    # DSO703: recorded exposure vs re-analysis
    recorded = (artifact.comm or {}).get("overlap")
    if recorded:
        drifts = []
        for field in ("wire_seconds", "exposed_wire_seconds"):
            rec_v, new_v = recorded.get(field), summary[field]
            if rec_v is None:
                continue
            tol = max(abs(new_v), 1e-12) * 0.05
            if abs(float(rec_v) - float(new_v)) > tol:
                drifts.append(f"{field} {rec_v} -> {new_v}")
        for field in ("collectives", "host_transfers"):
            rec_v = (recorded.get(field) or {}).get("total")
            if rec_v is not None and rec_v != summary[field]["total"]:
                drifts.append(
                    f"{field} {rec_v} -> {summary[field]['total']}")
        if drifts:
            out.append(_pdiag(
                artifact, "DSO703",
                "recorded overlap summary drifts from the HLO "
                f"re-analysis: {'; '.join(drifts)} (stale or tampered "
                "artifact)"))
    return out


def verify_program(artifact: ProgramArtifact) -> List[Diagnostic]:
    """All DSP6xx/DSO7xx/DSS8xx HLO-side diagnostics for one program
    artifact."""
    if not artifact.hlo:
        # a sidecar whose HLO text is missing/empty would otherwise
        # make every HLO-side rule early-return — "verified clean" on
        # exactly the stale/tampered-dump scenario DSP613 exists for
        return [_pdiag(
            artifact, "DSP613",
            "sidecar present but the program's HLO text is missing or "
            "empty — artifact unverifiable (stale or tampered dump; "
            "re-dump with profiling.program_dump enabled)")]
    return (check_donation(artifact) + check_collectives(artifact)
            + check_overlap(artifact) + check_sharding(artifact))


def verify_artifacts(artifacts) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for artifact in artifacts:
        out.extend(verify_program(artifact))
    out.extend(check_sharding_consistency(artifacts))
    out.sort(key=lambda d: (d.path, d.rule_id, d.message))
    return out


# ---------------------------------------------------------------------------
# DSP603: AST dataflow — read-after-donation in driver code
# ---------------------------------------------------------------------------

_NUMPY_ALLOC_FNS = {"zeros", "empty", "ones", "full", "asarray", "array",
                    "frombuffer", "copy", "ascontiguousarray",
                    "zeros_like", "empty_like"}
_MISSING = object()


def _literal_argnums(node) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums value -> positions tuple, None when the
    expression is computed (engine-style ``donate`` variables)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _donating_jit_spec(expr):
    """donate positions of the first ``jit(..., donate_argnums=...)``
    call inside ``expr`` (``_MISSING`` when none)."""
    for sub in ast.walk(expr):
        if not isinstance(sub, ast.Call):
            continue
        if call_name(sub).rsplit(".", 1)[-1] != "jit":
            continue
        for kw in sub.keywords:
            if kw.arg == "donate_argnums":
                return _literal_argnums(kw.value)
    return _MISSING


def _target_key(tgt) -> Optional[str]:
    if isinstance(tgt, ast.Name):
        return tgt.id
    if (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"):
        return f"self.{tgt.attr}"
    return None


def _callee_key(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if (isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"):
        return f"self.{call.func.attr}"
    return None


def _collect_donors(tree) -> Dict[str, Optional[Tuple[int, ...]]]:
    """Names (``x`` / ``self.x``) bound to donating jit callables
    anywhere in the module, with their donated positions (None =
    positions not statically known)."""
    donors: Dict[str, Optional[Tuple[int, ...]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        key = _target_key(node.targets[0])
        if key is None:
            continue
        spec = _donating_jit_spec(node.value)
        if spec is not _MISSING:
            donors[key] = spec
    return donors


def _is_numpy_alloc(expr) -> bool:
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in _NUMPY_ALLOC_FNS
            and isinstance(expr.func.value, ast.Name)
            and expr.func.value.id in ("np", "numpy"))


def _is_device_put(expr) -> bool:
    return (isinstance(expr, ast.Call)
            and call_name(expr).rsplit(".", 1)[-1] == "device_put")


def check_use_after_donation(pf: ParsedFile,
                             index=None) -> List[Diagnostic]:
    """The DSP603 dataflow pass over one module.

    Intra-procedural and name-based by design: only plain local names
    are tracked (engine code passing ``self.state[...]`` pytree slots
    that the call's outputs re-bind is the sanctioned pattern and never
    matches).  A later re-binding of the name clears the watch."""
    from .analysis import ModuleIndex, body_nodes

    if index is None:
        index = ModuleIndex(pf.tree)
    donors = _collect_donors(pf.tree)
    out: List[Diagnostic] = []
    for fn in index.functions:
        # last simple assignment per local name (for device_put / numpy
        # staging provenance), in source order
        assigns: Dict[str, ast.expr] = {}
        events = []  # (lineno, col, kind, payload)
        for node, _ in body_nodes(fn, index.node_map):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                events.append((node.lineno, node.col_offset, "assign",
                               (node.targets[0].id, node.value)))
            elif isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    events.append((node.lineno, node.col_offset, "store",
                                   node.id))
                elif isinstance(node.ctx, (ast.Del,)):
                    events.append((node.lineno, node.col_offset, "store",
                                   node.id))
                elif isinstance(node.ctx, ast.Load):
                    events.append((node.lineno, node.col_offset, "load",
                                   node))
            if isinstance(node, ast.Call):
                callee = _callee_key(node)
                if callee in donors:
                    events.append((node.lineno, node.col_offset, "donate",
                                   (node, donors[callee], callee)))
        # within one statement line: argument loads evaluate first, then
        # the donating call, then the target re-binding — so
        # ``acc = donor(acc)`` watches and immediately clears ``acc``
        _PRIO = {"load": 0, "donate": 1, "assign": 2, "store": 2}
        events.sort(key=lambda e: (e[0], _PRIO[e[2]], e[1]))

        # watched[name] -> (donating call node, callee, staged_numpy)
        watched: Dict[str, tuple] = {}
        for lineno, col, kind, payload in events:
            if kind == "assign":
                name, value = payload
                assigns[name] = value
                watched.pop(name, None)
            elif kind == "store":
                watched.pop(payload, None)
            elif kind == "donate":
                call, positions, callee = payload
                if positions is None:
                    # computed donate_argnums: only the high-confidence
                    # staged-numpy shape is worth flagging
                    cand = list(enumerate(call.args))
                else:
                    cand = [(i, call.args[i]) for i in positions
                            if i < len(call.args)]
                for i, arg in cand:
                    names = []
                    staged = False
                    src = arg
                    if isinstance(src, ast.Name):
                        names.append(src.id)
                        src = assigns.get(src.id, src)
                    if _is_device_put(src) and src.args \
                            and isinstance(src.args[0], ast.Name):
                        base = src.args[0].id
                        names.append(base)
                        staged = _is_numpy_alloc(assigns.get(base, base))
                    if positions is None and not staged:
                        continue
                    for nm in names:
                        watched[nm] = (call, callee, staged)
            elif kind == "load":
                node = payload
                info = watched.get(node.id)
                if info is None:
                    continue
                call_end = getattr(info[0], "end_lineno", info[0].lineno)
                if node.lineno <= (call_end or info[0].lineno):
                    continue
                call, callee, staged = info
                extra = (" — and it is a live numpy STAGING buffer whose "
                         "memory the runtime may free (heap corruption)"
                         if staged else "")
                # no line number in the message: baseline keys embed the
                # message verbatim, and line numbers drift with
                # unrelated edits (the diagnostic's own location already
                # points at the read site)
                out.append(diag(
                    pf, node, "DSP603",
                    f"'{node.id}' read after being donated to "
                    f"{callee}(...): the buffer may already be reused "
                    f"by its outputs{extra}"))
    return out


@register_file_checker
def check_donation_dataflow(pf: ParsedFile) -> List[Diagnostic]:
    return check_use_after_donation(pf)
