"""Robustness rules: swallowed-failure anti-patterns (DSE5xx).

The resilience subsystem's whole premise is that failures must be LOUD:
a guard can only escalate anomalies it gets to see.  A ``try`` block
that eats the exception breaks that chain silently — the classic way a
"fault-tolerant" training job turns into one that trains garbage for a
week.  Two shapes are flagged:

- **DSE501** — a bare ``except:`` clause.  Beyond hiding the error it
  also catches ``SystemExit``/``KeyboardInterrupt``, so it can eat the
  watchdog's teardown or a Ctrl-C.
- **DSE502** — an ``except Exception``/``BaseException`` (or bare)
  handler whose body does literally nothing (``pass`` / ``...``): the
  failure is not logged, not re-raised, not recorded — gone.

Handlers that narrow the exception type, log, re-raise, or return a
sentinel are all fine; the rules target only the discard-everything
shapes.  Legitimate sites (e.g. probing an optional backend API)
suppress with a reasoned pragma:
``# dslint: disable=DSE502 -- why``.
"""

import ast
from typing import List

from .core import (ParsedFile, Rule, diag, register_file_checker,
                   register_rule)

register_rule(Rule(
    id="DSE501", name="bare-except", severity="warning",
    summary="bare 'except:' clause",
    rationale="Catches EVERYTHING, including SystemExit and "
              "KeyboardInterrupt — it can eat a watchdog teardown or a "
              "Ctrl-C, and hides the real failure from the anomaly "
              "guard and the logs.",
    autofix_hint="Catch the narrowest exception type that can actually "
                 "occur (at widest 'except Exception'), and log or "
                 "re-raise."))

register_rule(Rule(
    id="DSE502", name="swallowed-exception", severity="warning",
    summary="except handler silently discards the failure (body is only "
            "pass/...)",
    rationale="A broad handler with an empty body erases the failure: "
              "nothing is logged, nothing is re-raised, and the "
              "resilience guard never sees the anomaly — the job keeps "
              "'succeeding' while broken.",
    autofix_hint="Log the exception (logger.warning('...: %s', e)), "
                 "re-raise, or record it; suppress with a reasoned "
                 "pragma only for genuinely-optional probes."))

_BROAD_TYPES = {"Exception", "BaseException"}


def _type_names(expr):
    """Exception class names named by a handler's type expression."""
    if expr is None:
        return set()
    nodes = expr.elts if isinstance(expr, ast.Tuple) else [expr]
    out = set()
    for node in nodes:
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _is_noop(stmt):
    return isinstance(stmt, ast.Pass) or (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Constant)
        and stmt.value.value is Ellipsis)


@register_file_checker
def check_robustness(pf: ParsedFile) -> List:
    out = []
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            bare = handler.type is None
            if bare:
                out.append(diag(
                    pf, handler, "DSE501",
                    "bare 'except:' catches SystemExit/KeyboardInterrupt "
                    "too; name the exception type (at widest 'except "
                    "Exception')"))
            broad = bare or (_type_names(handler.type) & _BROAD_TYPES)
            if broad and all(_is_noop(s) for s in handler.body):
                caught = ("everything" if bare
                          else "/".join(sorted(_type_names(handler.type)
                                               & _BROAD_TYPES)))
                out.append(diag(
                    pf, handler, "DSE502",
                    f"handler catches {caught} and silently discards it "
                    "(body is only pass/...); log, re-raise, or record "
                    "the failure"))
    return out
