"""dslint — TPU-correctness static analysis for DeepSpeed-TPU.

Three rule families (see ``docs/static_analysis.md``):

- **hot-path** (DSH1xx/DSH2xx): host-sync and device-transfer
  anti-patterns in code reachable from ``jax.jit``/``shard_map`` traces
  and in step-cadence engine driver code;
- **retrace** (DSR3xx): jit-cache hazards — mutable defaults, impure
  captures, unhashable static args, Python branches on traced values;
- **config-schema** (DSC4xx): the key/type/default schema extracted from
  the constants modules, with dead-key detection and a runtime
  ``validate_config_dict`` (unknown-key + "did you mean") that
  ``DeepSpeedConfig`` calls on every construction;
- **robustness** (DSE5xx): swallowed-failure patterns — bare
  ``except:`` and broad except-with-empty-body handlers that hide
  failures from the resilience guard and the logs;
- **programs** (DSP6xx): program-level semantics on the COMPILED
  artifacts — donation/aliasing safety (declared ``donate_argnums``
  must materialize as ``input_output_alias`` entries; AST dataflow
  flags reads-after-donation) and collective semantics (parameter
  sums spanning non-data mesh axes, psum-for-pmean, comm-ledger
  drift), via ``--programs <run_dir>`` or
  ``engine.verify_programs()``.

Suppression: ``# dslint: disable=<rule-id>[,<rule-id>...] [-- reason]``
inline on the flagged line, or standalone on the line above.

Stdlib-only by design — importable before jax, usable in any CI image.
"""

# importing the rule modules populates the registries
from . import hotpath, programs, retrace, robustness, schema  # noqa: F401
from .cli import failing, lint_paths, main
from .core import (RULES, Diagnostic, Rule, SourceReadError,
                   register_rule, rule_catalog, rule_family)
from .schema import (ConfigIssue, dead_key_diagnostics, extract_schema,
                     get_schema, validate_config_dict)

__all__ = [
    "RULES", "Rule", "Diagnostic", "register_rule", "lint_paths",
    "failing", "main", "extract_schema", "get_schema",
    "validate_config_dict", "dead_key_diagnostics", "ConfigIssue",
    "rule_catalog", "rule_family", "SourceReadError",
]
