"""Hot-path rules: host-sync / device-transfer anti-patterns.

Two sub-families with very different badness models:

- **DSH1xx (in-jit, error)** — code reachable from a ``jax.jit`` /
  ``shard_map`` trace.  A host sync here either fails to trace outright
  or (worse) silently executes at *trace time* and bakes a stale value
  into the compiled program.  On remote-attached TPUs a single stray
  sync costs a full wire round-trip (~70-100 ms) per dispatch — 10×+ a
  tuned step time.

- **DSH2xx (step-cadence driver, warning)** — the host-side engine loop
  (``train_batch`` / ``step`` / ``forward`` / ``backward`` and the
  ``self.*`` helpers they call).  Host syncs here are *legal* but each
  separate ``device_get``/`.item()` is its own blocking round-trip; N
  scalars fetched one-by-one cost N latencies when one batched
  ``jax.device_get(pytree)`` costs one.
"""

import ast
from typing import List

from .analysis import ModuleIndex, body_nodes
from .core import (ParsedFile, Rule, call_name, diag, dotted_name,
                   register_file_checker, register_rule)

# -- rule catalog -----------------------------------------------------------

register_rule(Rule(
    id="DSH101", name="hot-item-sync", severity="error",
    summary=".item()/.tolist() on a value inside jit-traced code",
    rationale="Forces a device→host transfer inside a traced function: "
              "fails under jit, or executes at trace time and bakes a "
              "stale constant into the compiled program.",
    autofix_hint="Keep the value on device (jnp ops), or return it from "
                 "the jitted function and fetch it host-side."))

register_rule(Rule(
    id="DSH102", name="hot-scalar-cast", severity="error",
    summary="float()/int()/bool() of a traced value inside jit-traced code",
    rationale="Python scalar conversion of a tracer raises "
              "ConcretizationTypeError — or silently freezes a trace-time "
              "constant if the value happens to be concrete. Shape/dtype "
              "arithmetic (x.shape, len(...)) is static and exempt.",
    autofix_hint="Use jnp casts (x.astype(...)) on device; fetch scalars "
                 "outside the jitted function."))

register_rule(Rule(
    id="DSH103", name="hot-host-materialize", severity="error",
    summary="np.asarray/np.array/jax.device_get inside jit-traced code",
    rationale="Materializes a traced array on the host: a hidden sync "
              "per call, and numpy results are trace-time constants that "
              "do not update step to step.",
    autofix_hint="Use jnp.asarray (traced) inside jit; reserve numpy for "
                 "host-side code or jax.pure_callback."))

register_rule(Rule(
    id="DSH104", name="hot-print", severity="error",
    summary="print() inside jit-traced code",
    rationale="Executes once at trace time, printing tracer reprs — not "
              "per step, not values. Silently misleading.",
    autofix_hint="Use jax.debug.print(...) for traced values."))

register_rule(Rule(
    id="DSH105", name="hot-wall-clock", severity="error",
    summary="time.time()/perf_counter() inside jit-traced code",
    rationale="Evaluates once at trace time; every execution of the "
              "compiled program sees the same frozen timestamp.",
    autofix_hint="Time around the dispatch on the host, fencing with a "
                 "device_get of an output (see profiling/step_profiler)."))

register_rule(Rule(
    id="DSH106", name="hot-device-loop", severity="error",
    summary="Python loop over jax.devices() inside jit-traced code",
    rationale="Per-device Python loops in traced code unroll at trace "
              "time into device_count copies of the body — and retrace "
              "when topology changes. SPMD collectives express this "
              "without unrolling.",
    autofix_hint="Use mesh axes + collectives (psum/all_gather) or "
                 "shard_map instead of enumerating devices."))

register_rule(Rule(
    id="DSH201", name="driver-item-sync", severity="warning",
    summary=".item() in step-cadence engine driver code",
    rationale=".item() blocks on one scalar: a full host round-trip on "
              "the step critical path, serializing host prep against "
              "device compute.",
    autofix_hint="Batch with other fetches via one jax.device_get(pytree) "
                 "at a coarser cadence (e.g. steps_per_print)."))

register_rule(Rule(
    id="DSH202", name="driver-sync-in-loop", severity="warning",
    summary="device transfer inside a Python loop in driver code",
    rationale="One blocking round-trip per iteration; a loop over N "
              "leaves costs N wire latencies where a single "
              "jax.device_get of the whole list costs one.",
    autofix_hint="Hoist: fetch the entire container with one "
                 "jax.device_get(...) before the loop."))

register_rule(Rule(
    id="DSH204", name="driver-memory-introspection", severity="warning",
    summary="memory_stats()/memory_analysis() on the per-step hot path",
    rationale="Device memory introspection is a host-side runtime query "
              "per device per call; on the step path it serializes host "
              "prep against the runtime and breaks the telemetry "
              "zero-new-syncs ledger contract (memory watermarks are "
              "sampled only at the steps_per_print cadence, and "
              "memory_analysis belongs at compile time).",
    autofix_hint="Route through profiling.memory: device_memory_summary "
                 "at the existing steps_per_print batched fetch, "
                 "MemoryLedger.record at program-build time."))

register_rule(Rule(
    id="DSH205", name="driver-skew-export", severity="warning",
    summary="latency/skew/fingerprint telemetry export outside the "
            "steps_per_print cadence in driver code",
    rationale="Per-rank run-dir exchange (latency-ring snapshots, the "
              "latency-rank*.json publish/read pair, and the integrity "
              "plane's integrity-rank*.json fingerprint publish/read/"
              "vote) does host arithmetic plus run-dir file I/O: cheap "
              "at print cadence, a per-step cost multiplier on the hot "
              "path.  The contract for both families is that they ride "
              "the existing batched steps_per_print fetch, adding zero "
              "per-step work.",
    autofix_hint="Call latency_snapshot/publish_rank_latency/"
                 "read_fleet_latencies (and publish_rank_fingerprint/"
                 "read_fleet_fingerprints/note_fingerprint) only from "
                 "code reached through an `if ... steps_per_print ...:` "
                 "guard (e.g. the engine's _sample_comm_skew / "
                 "_sample_integrity)."))

register_rule(Rule(
    id="DSH203", name="driver-unbatched-sync", severity="warning",
    summary="multiple separate host-sync sites in one driver function",
    rationale="Each device_get/.item()/sync-property read is an "
              "independent blocking round-trip; unrelated scalars fetched "
              "separately multiply per-step wire latency.",
    autofix_hint="Fetch together: jax.device_get((a, b, c)) is one "
                 "transfer. Suppress when sites run at different cadences."))

# -- matchers ---------------------------------------------------------------

_NUMPY_NAMES = {"np", "numpy"}
_SHAPEISH_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}
_STATIC_CALLS = {"len", "getattr", "prod", "np.prod", "numpy.prod", "ord",
                 "range", "enumerate", "zip", "isinstance", "hash", "repr",
                 # round() of a tracer fails loudly on its own; in practice
                 # int(round(x)) sites are host-float kernel-parameter math
                 "round"}
_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.process_time", "datetime.now", "datetime.utcnow",
                "datetime.datetime.now", "datetime.datetime.utcnow"}


def _is_item_call(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in ("item", "tolist") and not node.args
            and not node.keywords)


def _is_device_get(node: ast.Call) -> bool:
    name = call_name(node)
    return name.rsplit(".", 1)[-1] == "device_get"


def _is_np_materialize(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in ("asarray", "array"):
        return False
    base = node.func.value
    return isinstance(base, ast.Name) and base.id in _NUMPY_NAMES


def _is_static_expr(node) -> bool:
    """Shape/dtype/len arithmetic is static under tracing — exempt from
    DSH102 even though it syntactically casts to a Python scalar."""
    if isinstance(node, ast.Constant):
        return True
    has_ref = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _SHAPEISH_ATTRS:
            return True
        if isinstance(sub, ast.Call) and call_name(sub) in _STATIC_CALLS:
            return True
        if isinstance(sub, (ast.Name, ast.Attribute)):
            has_ref = True
    # pure literal arithmetic (e.g. float(1 << 32)) references no values
    return not has_ref


def _is_scalar_cast(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")):
        return False
    if len(node.args) != 1 or node.keywords:
        return False
    return not _is_static_expr(node.args[0])


_MEMORY_INTROSPECTION_ATTRS = ("memory_stats", "memory_analysis")


def _is_memory_introspection(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in _MEMORY_INTROSPECTION_ATTRS)


def _is_device_enum(expr) -> bool:
    return (isinstance(expr, ast.Call)
            and call_name(expr).rsplit(".", 1)[-1] in ("devices",
                                                       "local_devices")
            and dotted_name(getattr(expr.func, "value", None)) == "jax")


# -- in-jit checks ----------------------------------------------------------

def _check_hot_function(pf: ParsedFile, index: ModuleIndex, fn) -> List:
    out = []
    where = f"in jit-traced '{fn.qualname}'"
    for node, _ in body_nodes(fn, index.node_map):
        if isinstance(node, ast.Call):
            if _is_item_call(node):
                out.append(diag(pf, node, "DSH101",
                                f".{node.func.attr}() {where}: host sync "
                                "inside the compiled program"))
            elif _is_device_get(node) or _is_np_materialize(node):
                out.append(diag(pf, node, "DSH103",
                                f"{call_name(node)}(...) {where}: "
                                "materializes a traced value on host"))
            elif _is_scalar_cast(node):
                out.append(diag(pf, node, "DSH102",
                                f"{node.func.id}(...) {where}: Python "
                                "scalar conversion of a traced value"))
            elif isinstance(node.func, ast.Name) and node.func.id == "print":
                out.append(diag(pf, node, "DSH104",
                                f"print() {where}: runs once at trace "
                                "time; use jax.debug.print"))
            elif _is_memory_introspection(node):
                out.append(diag(pf, node, "DSH204",
                                f".{node.func.attr}() {where}: memory "
                                "introspection evaluates once at trace "
                                "time and is a per-device host query"))
            elif call_name(node) in _CLOCK_CALLS:
                out.append(diag(pf, node, "DSH105",
                                f"{call_name(node)}() {where}: wall clock "
                                "freezes at trace time"))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if _is_device_enum(node.iter):
                out.append(diag(pf, node, "DSH106",
                                f"loop over {call_name(node.iter)}() "
                                f"{where}: unrolls per device at trace "
                                "time"))
    return out


# -- step-cadence driver checks --------------------------------------------

DRIVER_CLASS_MARKERS = ("Engine", "Scaler", "Frontend")
DRIVER_METHODS = {
    "train_batch", "step", "forward", "backward", "eval_batch", "__call__",
    "_train_batch_stepwise", "_eval_one", "train_step",
    "has_overflow", "has_overflow_serial", "update_scale",
}


def _driver_roots(index: ModuleIndex):
    roots = set()
    for cls in index.classes:
        if not any(m in cls.name for m in DRIVER_CLASS_MARKERS):
            continue
        for name, fn in index.methods.get(cls.name, {}).items():
            if name in DRIVER_METHODS:
                roots.add(fn)
    return roots


def _mentions_steps_per_print(node) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "steps_per_print":
            return True
        if isinstance(sub, ast.Name) and sub.id == "steps_per_print":
            return True
    return False


def _guarded_call_ids(fn, node_map):
    """ids of Call nodes in ``fn``'s own body that are lexically inside
    an ``if`` whose test mentions ``steps_per_print`` — the print-cadence
    guard the DSH205 skew-export contract keys on."""
    guarded = set()

    def walk(node, in_guard):
        if id(node) in node_map:
            return  # nested def: its body is its own FuncNode
        if isinstance(node, ast.If):
            walk_children(node.test, in_guard)
            body_guard = in_guard or _mentions_steps_per_print(node.test)
            for child in node.body:
                walk_children(child, body_guard, top=True)
            for child in node.orelse:
                walk_children(child, in_guard, top=True)
            return
        if isinstance(node, ast.Call) and in_guard:
            guarded.add(id(node))
        walk_children(node, in_guard)

    def walk_children(node, in_guard, top=False):
        if top:
            walk(node, in_guard)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, in_guard)

    root = fn.node
    if isinstance(root, ast.Lambda):
        walk(root.body, False)
    else:
        for stmt in root.body:
            walk(stmt, False)
    return guarded


def _driver_closure(index: ModuleIndex, roots):
    """(closure, unguarded) — roots + same-class methods reached through
    self-calls (jit-hot functions are covered by the DSH1xx walk
    instead).  ``unguarded`` is the subset reachable from a root through
    a call chain with NO ``steps_per_print`` guard on any edge: per-step
    code.  Members of the closure absent from ``unguarded`` run only at
    the print cadence (the DSH205 skew-export contract)."""
    seen = set(roots)
    unguarded = set(roots)
    frontier = list(roots)
    while frontier:
        fn = frontier.pop()
        guarded_ids = _guarded_call_ids(fn, index.node_map)
        for node, _ in body_nodes(fn, index.node_map):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                target = index.resolve_self_attr(node.func.attr, fn)
                if target is None or target in index.hot:
                    continue
                edge_unguarded = (fn in unguarded
                                  and id(node) not in guarded_ids)
                if target not in seen:
                    seen.add(target)
                    frontier.append(target)
                if edge_unguarded and target not in unguarded:
                    # re-walk: its own edges now propagate unguarded
                    unguarded.add(target)
                    frontier.append(target)
    return seen - index.hot, unguarded - index.hot


def _sync_properties(index: ModuleIndex, cls_name: str):
    """Names of @property methods on the class whose body performs a host
    sync — reading them from driver code is a hidden round-trip."""
    out = set()
    for name, fn in index.methods.get(cls_name, {}).items():
        if not fn.is_property:
            continue
        for node, _ in body_nodes(fn, index.node_map):
            if isinstance(node, ast.Call) and (_is_device_get(node)
                                               or _is_item_call(node)):
                out.add(name)
                break
    return out


# latency/skew export surface (profiling/step_profiler.StepLatencyRing
# + profiling/comm's per-rank exchange) plus the integrity plane's
# fingerprint exchange (resilience/integrity.py: the publish/read/vote
# APIs — NOT the fleet heartbeat's beat(), which is per-step by design
# at O(1) throttled host work): print-cadence-only by contract
_SKEW_EXPORT_CALLS = {"latency_snapshot", "publish_rank_latency",
                      "read_fleet_latencies", "publish_rank_fingerprint",
                      "read_fleet_fingerprints", "note_fingerprint",
                      # serving twin (inference/resilience.py): the
                      # weight-fingerprint publish/read/vote surface —
                      # file I/O per call, print-cadence-only by the
                      # same contract
                      "publish_weight_fingerprint",
                      "read_fleet_weight_fingerprints",
                      "note_weight_fingerprint",
                      # serving observability (inference/observability):
                      # the window close + fleet-gauge exporters — event
                      # emission and window resets, print-cadence-only
                      # by the same contract
                      "export_serving_window",
                      "export_serving_gauges"}


def _is_skew_export(node: ast.Call) -> bool:
    return call_name(node).rsplit(".", 1)[-1] in _SKEW_EXPORT_CALLS


def _check_driver_function(pf: ParsedFile, index: ModuleIndex, fn,
                           cadence_only=False) -> List:
    out = []
    sync_props = (_sync_properties(index, fn.class_name)
                  if fn.class_name else set())
    guarded_ids = (_guarded_call_ids(fn, index.node_map)
                   if not cadence_only else None)
    sites = []  # (node, kind, in_loop)
    for node, in_loop in body_nodes(fn, index.node_map):
        if isinstance(node, ast.Call):
            if (not cadence_only and _is_skew_export(node)
                    and id(node) not in guarded_ids):
                # reachable per step AND not under a local
                # steps_per_print guard: the skew export would run on
                # the hot path
                out.append(diag(
                    pf, node, "DSH205",
                    f"{call_name(node)}(...) in driver '{fn.qualname}': "
                    "latency/skew export on the per-step path; move it "
                    "under the steps_per_print cadence guard"))
            if _is_item_call(node):
                sites.append((node, f".{node.func.attr}()", in_loop))
                out.append(diag(pf, node, "DSH201",
                                f".{node.func.attr}() in driver "
                                f"'{fn.qualname}': blocking per-scalar "
                                "host sync on the step path"))
            elif _is_memory_introspection(node):
                out.append(diag(
                    pf, node, "DSH204",
                    f".{node.func.attr}() in driver '{fn.qualname}': "
                    "per-device memory introspection on the step path; "
                    "sample via profiling.memory.device_memory_summary "
                    "at the steps_per_print cadence instead"))
            elif _is_device_get(node):
                sites.append((node, "jax.device_get", in_loop))
            elif _is_np_materialize(node):
                # np.asarray of a device array is an implicit device_get;
                # only the in-loop form is flagged (a single bulk copy on
                # host data is idiomatic and type-invisible to the linter)
                if in_loop:
                    sites.append((node, f"{call_name(node)}", in_loop))
        elif (isinstance(node, ast.Attribute)
              and isinstance(node.ctx, ast.Load)
              and isinstance(node.value, ast.Name)
              and node.value.id == "self" and node.attr in sync_props):
            sites.append((node, f"self.{node.attr} (sync property)",
                          in_loop))
    for node, kind, in_loop in sites:
        if in_loop:
            out.append(diag(pf, node, "DSH202",
                            f"{kind} inside a Python loop in driver "
                            f"'{fn.qualname}': one round-trip per "
                            "iteration; hoist into one batched "
                            "jax.device_get"))
    if len(sites) >= 2:
        for node, kind, _ in sites[1:]:
            out.append(diag(pf, node, "DSH203",
                            f"{kind} in driver '{fn.qualname}': "
                            f"{len(sites)} separate host-sync sites in "
                            "this function; batch into one "
                            "jax.device_get(pytree)"))
    return out


@register_file_checker
def check_hotpath(pf: ParsedFile) -> List:
    index = ModuleIndex(pf.tree)
    out = []
    for fn in sorted(index.hot, key=lambda f: f.node.lineno):
        out.extend(_check_hot_function(pf, index, fn))
    closure, unguarded = _driver_closure(index, _driver_roots(index))
    for fn in sorted(closure, key=lambda f: f.node.lineno):
        # cadence_only: every path from a driver root to fn crosses a
        # steps_per_print guard — skew export is in-contract there
        out.extend(_check_driver_function(pf, index, fn,
                                          cadence_only=fn not in unguarded))
    return out
