"""Config-schema extraction + validation.

The reference DeepSpeed config is a loosely-typed JSON dict read through
``dict.get(key, default)``: a misspelled key silently reverts to its
default.  This module derives the canonical key/type/default schema
*statically* from the package's own constants modules (the ``KEY =
"literal"`` / ``KEY_DEFAULT = value`` pairs in ``runtime/constants.py``
and the feature-config modules), then:

- ``validate_config_dict()`` — runtime unknown-key detection with
  difflib "did you mean" suggestions, called from ``DeepSpeedConfig``
  (warn by default; ``"strict_config": true`` raises), and

- ``dead_key_diagnostics()`` — the static inverse: every declared key
  constant must be *read* somewhere in the package, else declaring it
  was a lie (DSC401).

Stdlib-only and import-free with respect to the package itself: the
constants modules are parsed as AST, never imported, so the validator
works before (and independently of) jax initialization.
"""

import ast
import difflib
import os
import re
from typing import Dict, List, NamedTuple, Optional

from .core import Diagnostic, Rule, register_rule

register_rule(Rule(
    id="DSC401", name="config-dead-key", severity="warning",
    summary="declared config-key constant is never read by the package",
    rationale="A declared-but-unread key is worse than an unknown one: "
              "users set it, the dict carries it, and nothing ever "
              "honors it — the exact silent-default failure mode this "
              "schema exists to kill.",
    autofix_hint="Wire the key into the config parser, or delete the "
                 "constant; suppress only documented parity "
                 "placeholders."))

register_rule(Rule(
    id="DSC402", name="config-unknown-key", severity="error",
    summary="unknown config key (possible misspelling)",
    rationale="dict.get(key, default) lookups silently revert misspelled "
              "keys to defaults — e.g. 'gradient_acumulation_steps' "
              "trains with accumulation 1 and nobody notices.",
    autofix_hint="Fix the spelling (see the suggestion) or add the key "
                 "to the schema's constants module."))


class KeyInfo(NamedTuple):
    key: str                  # JSON key string
    const_name: str           # python constant name
    section: Optional[str]    # None = top-level
    default: object           # extracted literal (None if no *_DEFAULT)
    has_default: bool
    source: str               # module path the constant came from
    line: int


class ConfigSchema(NamedTuple):
    top_level: Dict[str, KeyInfo]
    sections: Dict[str, Dict[str, KeyInfo]]
    # one-level-nested sub-blocks: (section, sub-block key) -> sub-keys
    # (e.g. zero_optimization.offload_state_dtype.{master,momentum,...});
    # None (not a shared mutable {}) when constructed without it
    nested: Optional[Dict] = None

    def all_keys(self) -> Dict[str, KeyInfo]:
        out = dict(self.top_level)
        for sec in self.sections.values():
            out.update(sec)
        for sub in (self.nested or {}).values():
            out.update(sub)
        return out


class ConfigIssue(NamedTuple):
    key: str
    section: Optional[str]    # section the unknown key appeared under
    suggestion: Optional[str]
    message: str


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------

def package_root() -> str:
    """deepspeed_tpu/ directory (this file is tools/dslint/schema.py)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# (relative module, default section for unprefixed names, names that are
# plain value-constants rather than config keys)
_CONSTANT_MODULES = (
    ("runtime/constants.py", None, {
        # optimizer names / zero stage ints / modes: values, not keys
        "ADAM_OPTIMIZER", "LAMB_OPTIMIZER", "ONEBIT_ADAM_OPTIMIZER",
        "DEEPSPEED_OPTIMIZERS", "SPARSE_DENSE_MODE", "SPARSE_FIXED_MODE",
        "SPARSE_VARIABLE_MODE", "SPARSE_BIGBIRD_MODE",
        "SPARSE_BSLONGFORMER_MODE", "ROUTE_PREFIX",
    }),
    ("runtime/activation_checkpointing/config.py", "activation_checkpointing",
     set()),
    ("profiling/config.py", "flops_profiler", set()),
    ("elasticity/constants.py", "elasticity", {
        "MINIMUM_DEEPSPEED_VERSION", "DEEPSPEED_ELASTICITY_CONFIG",
    }),
)

# constant-name prefix -> config section (for runtime/constants.py, whose
# single module declares keys for many JSON subsections)
_SECTION_PREFIXES = (
    ("FP16_", "fp16"), ("BF16_", "bf16"), ("AMP_", "amp"),
    ("TENSORBOARD_", "tensorboard"), ("ZERO_", "zero_optimization"),
    ("PIPELINE_", "pipeline"), ("PLD_", "progressive_layer_drop"),
    ("MESH_", "mesh"), ("SPARSE_", "sparse_attention"),
    ("CHECKPOINT_", "checkpoint"), ("RING_ATTENTION_", "ring_attention"),
    ("RESILIENCE_", "resilience"), ("TELEMETRY_", "telemetry"),
    ("COMPILATION_", "compilation"), ("PROFILING_", "profiling"),
    ("ACT_CHKPT_", "activation_checkpointing"),
    ("FLOPS_PROFILER_", "flops_profiler"),
    ("INFERENCE_", "inference"),
)

# constant-name prefix -> (section, sub-block key) for one-level-nested
# config blocks; checked BEFORE the flat section prefixes (a nested
# prefix is always a strict extension of its section prefix).  The
# sub-block's own name constant (no trailing segment) stays an ordinary
# key of the parent section.
_NESTED_SECTION_PREFIXES = (
    ("ZERO_OFFLOAD_STATE_DTYPE_",
     ("zero_optimization", "offload_state_dtype")),
    ("INFERENCE_SLO_", ("inference", "slo")),
)

# prefixed names that are nonetheless TOP-LEVEL json keys
_TOP_LEVEL_OVERRIDES = {
    "ZERO_ALLOW_UNTESTED_OPTIMIZER", "SPARSE_GRADIENTS",
    # section names themselves (FP16 = "fp16", ...) carry no underscore
    # prefix and fall through to top-level naturally
}

# exact-name section placements the prefix convention cannot express
_SECTION_NAME_OVERRIDES = {
    "LEGACY_FUSION": "optimizer", "TYPE": "optimizer",
    "OPTIMIZER_PARAMS": "optimizer", "SCHEDULER_PARAMS": "scheduler",
    "MAX_GRAD_NORM": "optimizer",
}

# keys read straight off the top-level dict without a constant (raw
# ``param_dict.get("...")`` sites in runtime/config.py + engine.py)
SUPPLEMENTAL_TOP_LEVEL_KEYS = ("seed", "prng_impl", "vocabulary_size")

# sections whose sub-schema is hand-listed (their keys live inline in
# config parsing, not as prefixed constants)
_EXPLICIT_SECTIONS = {
    "optimizer": ("type", "params", "legacy_fusion"),
    "scheduler": ("type", "params"),
}

# dict-valued sections whose *contents* are free-form (validated by their
# consumers, not by key-schema): optimizer/scheduler params already nest
# under 'params' which we skip.
_FREEFORM_SUBKEYS = {"params"}


def _parse_constants(path: str):
    """(name -> (string_value, line)) and (name -> default literal)."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    strings, defaults, env = {}, {}, {}
    _missing = object()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        try:
            value = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            # aliased constants (ZERO_STAGE_DEFAULT =
            # ZERO_OPTIMIZATION_DISABLED) resolve through the module's own
            # earlier literal bindings
            if isinstance(node.value, ast.Name):
                value = env.get(node.value.id, _missing)
                if value is _missing:
                    continue
            else:
                continue
        env[name] = value
        if name.endswith("_DEFAULT"):
            defaults[name] = value
        elif isinstance(value, str):
            strings[name] = (value, node.lineno)
    return strings, defaults


def extract_schema(root: Optional[str] = None) -> ConfigSchema:
    root = root or package_root()
    top: Dict[str, KeyInfo] = {}
    sections: Dict[str, Dict[str, KeyInfo]] = {}
    nested: Dict = {}

    for rel, default_section, excluded in _CONSTANT_MODULES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        strings, defaults = _parse_constants(path)
        for name, (key, line) in strings.items():
            if name in excluded:
                continue
            section = default_section
            nest = None
            if rel == "runtime/constants.py":
                for prefix, nest_addr in _NESTED_SECTION_PREFIXES:
                    if name.startswith(prefix):
                        nest = nest_addr
                        break
                if nest is not None:
                    section = None
                elif name in _TOP_LEVEL_OVERRIDES:
                    section = None
                elif name in _SECTION_NAME_OVERRIDES:
                    section = _SECTION_NAME_OVERRIDES[name]
                else:
                    for prefix, sec in _SECTION_PREFIXES:
                        if name.startswith(prefix):
                            section = sec
                            break
            if nest is not None:
                nested.setdefault(nest, {}).setdefault(key, KeyInfo(
                    key=key, const_name=name, section="%s.%s" % nest,
                    default=defaults.get(name + "_DEFAULT"),
                    has_default=(name + "_DEFAULT") in defaults,
                    source=rel, line=line))
                continue
            # a section-name constant (FP16 = "fp16") stays top-level even
            # when the module maps to a section (ACT_CHKPT, FLOPS_PROFILER,
            # ELASTICITY declare their own section key)
            if section is not None and key == section:
                section = None
            info = KeyInfo(key=key, const_name=name, section=section,
                           default=defaults.get(name + "_DEFAULT"),
                           has_default=(name + "_DEFAULT") in defaults,
                           source=rel, line=line)
            if section is None:
                top.setdefault(key, info)
            else:
                sections.setdefault(section, {}).setdefault(key, info)

    for sec, keys in _EXPLICIT_SECTIONS.items():
        bucket = sections.setdefault(sec, {})
        for key in keys:
            bucket.setdefault(key, KeyInfo(
                key=key, const_name="", section=sec, default=None,
                has_default=False, source="<explicit>", line=0))
    for key in SUPPLEMENTAL_TOP_LEVEL_KEYS:
        top.setdefault(key, KeyInfo(
            key=key, const_name="", section=None, default=None,
            has_default=False, source="<supplemental>", line=0))
    return ConfigSchema(top_level=top, sections=sections, nested=nested)


_SCHEMA_CACHE: Optional[ConfigSchema] = None


def get_schema() -> ConfigSchema:
    global _SCHEMA_CACHE
    if _SCHEMA_CACHE is None:
        _SCHEMA_CACHE = extract_schema()
    return _SCHEMA_CACHE


# ---------------------------------------------------------------------------
# Runtime validation (wired into DeepSpeedConfig)
# ---------------------------------------------------------------------------

def _suggest(key: str, candidates) -> Optional[str]:
    matches = difflib.get_close_matches(key, list(candidates), n=1,
                                        cutoff=0.75)
    return matches[0] if matches else None


def validate_config_dict(param_dict: dict,
                         schema: Optional[ConfigSchema] = None,
                         extra_keys=()) -> List[ConfigIssue]:
    """Unknown-key scan of a DeepSpeed config dict.

    Returns one :class:`ConfigIssue` per unknown top-level key and per
    unknown sub-key of a known section, each with a "did you mean"
    suggestion when a close schema key exists.  Free-form subtrees
    (``optimizer.params`` / ``scheduler.params``) are skipped.
    """
    schema = schema or get_schema()
    issues: List[ConfigIssue] = []
    known_top = set(schema.top_level) | set(schema.sections) | set(extra_keys)

    for key, value in param_dict.items():
        if key not in known_top:
            sug = _suggest(key, known_top)
            hint = f"; did you mean '{sug}'?" if sug else ""
            issues.append(ConfigIssue(
                key=key, section=None, suggestion=sug,
                message=f"unknown config key '{key}'{hint} (unknown keys "
                        "are silently ignored by dict.get lookups)"))
            continue
        section_schema = schema.sections.get(key)
        if section_schema is None or not isinstance(value, dict):
            continue  # scalar key, free-form section, or deprecated bool
        known_sub = set(section_schema) | _FREEFORM_SUBKEYS
        for sub, sub_value in value.items():
            if sub in known_sub:
                # one-level-nested sub-block (e.g. zero_optimization.
                # offload_state_dtype): descend when a nested schema
                # exists and the value is the dict form (shorthand
                # strings are validated by the section parser)
                nested_schema = (schema.nested or {}).get((key, sub))
                if nested_schema is not None and isinstance(sub_value,
                                                            dict):
                    for k2 in sub_value:
                        if k2 in nested_schema:
                            continue
                        sug = _suggest(k2, nested_schema)
                        hint = f"; did you mean '{sug}'?" if sug else ""
                        issues.append(ConfigIssue(
                            key=k2, section=f"{key}.{sub}",
                            suggestion=sug,
                            message=f"unknown key '{k2}' in config "
                                    f"sub-block '{key}.{sub}'{hint}"))
                continue
            sug = _suggest(sub, known_sub)
            hint = f"; did you mean '{sug}'?" if sug else ""
            issues.append(ConfigIssue(
                key=sub, section=key, suggestion=sug,
                message=f"unknown key '{sub}' in config section "
                        f"'{key}'{hint}"))
    return issues


def issues_to_diagnostics(issues: List[ConfigIssue],
                          path: str) -> List[Diagnostic]:
    return [Diagnostic(path=path, line=1, col=1, rule_id="DSC402",
                       message=i.message) for i in issues]


# ---------------------------------------------------------------------------
# Static dead-key detection (DSC401)
# ---------------------------------------------------------------------------

def _package_sources(root: str, skip_rel) -> List[str]:
    """Concatenable source list for reference scanning: every package .py
    except the constants modules themselves and the linter package."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        if rel_dir.split(os.sep)[0] == "tools":
            dirnames[:] = []
            continue
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            rel = os.path.normpath(os.path.join(rel_dir, fname))
            if rel in skip_rel:
                continue
            with open(os.path.join(dirpath, fname), "r",
                      encoding="utf-8") as f:
                out.append(f.read())
    return out


def dead_key_diagnostics(root: Optional[str] = None) -> List[Diagnostic]:
    """DSC401: key constants in ``runtime/constants.py`` that no package
    module references — declared configuration surface nothing honors."""
    root = root or package_root()
    rel = "runtime/constants.py"
    path = os.path.join(root, rel)
    if not os.path.isfile(path):
        return []
    strings, _ = _parse_constants(path)
    excluded = next(x for r, _, x in _CONSTANT_MODULES if r == rel)
    corpus = "\n".join(_package_sources(
        root, skip_rel={os.path.normpath(rel)}))
    diags = []
    for name, (key, line) in sorted(strings.items(),
                                    key=lambda kv: kv[1][1]):
        if name in excluded:
            continue
        if re.search(rf"\b{re.escape(name)}\b", corpus) is None:
            diags.append(Diagnostic(
                path=path, line=line, col=1, rule_id="DSC401",
                message=f"config key constant {name} (json key "
                        f"'{key}') is never read outside constants.py: "
                        "setting it in a config silently does nothing"))
    return diags
