"""Developer tooling for DeepSpeed-TPU (kept import-light: nothing here
may import jax — tools must work in environments without an accelerator
stack, and ``runtime/config.py`` imports the config-schema validator from
``tools.dslint.schema`` at engine-construction time)."""
