"""Bench regression gate: diff two driver-bench JSON artifacts.

``python tools/bench_diff.py OLD.json NEW.json`` (or
``python -m deepspeed_tpu.telemetry report --diff OLD NEW``) compares
two ``BENCH_r*.json`` records field by field using the per-field
thresholds registered in :mod:`.bench_schema` — the BENCH trajectory
becomes a *checked* artifact instead of a pile of JSON to eyeball.

Classification per shared numeric field (direction + rel_tol from
``bench_schema.threshold_for``):

- **regressed** — moved against its direction by more than rel_tol;
- **improved** — moved with its direction by more than rel_tol;
- **ok** — within tolerance;
- **info** — no threshold registered (diffed, never gated).

Direction ``zero`` handles SIGNED optimum-at-zero metrics
(``step_unexplained_fraction``: negative = over-prediction, positive =
under-prediction, 0 = perfect reconciliation): the gate compares
MAGNITUDES with the tolerance as an absolute band — ``|new| - |old| >
tol`` regresses, ``< -tol`` improves.  A relative lower-is-better gate
would flag -0.10 → 0.0 as a regression and wave -0.10 → -0.50 through.

Added/removed fields and non-numeric changes are reported as such.
Exit code 1 when any field regressed (``--no-fail`` suppresses), 0
otherwise.  ``--self-check A B C ...`` diffs each consecutive pair and
always exits 0 — the CI mode over the checked-in historical sequence
(threshold violations report; history is evidence, not a failure).

Stdlib-only (like the rest of the telemetry readers): runs anywhere the
artifacts are mounted, no jax required.
"""

import argparse
import json
import numbers
import sys

from .bench_schema import threshold_for

STATUS_ORDER = ("regressed", "improved", "changed", "added", "removed",
                "ok", "info")


def load_bench_record(path):
    """A bench record from ``path`` — the raw one-line record, the
    driver wrapper ``{"parsed": {...}, ...}``, or a MULTICHIP driver
    blob ``{"n_devices", "rc", "ok", "skipped", "tail"}``.

    MULTICHIP blobs: since round 8 ``dryrun_multichip`` prints one
    structured JSON record (``multichip_schema_version`` + per-leg
    ``leg_*`` fields) as its last line, which the driver captures inside
    ``tail`` — extract it so the diff gates legs, not log prose.  Legacy
    blobs (rounds ≤7) degrade to their scalar fields with the prose
    dropped."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "tail" in data:
        for line in reversed(str(data["tail"]).splitlines()):
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "multichip_schema_version" in rec:
                if data.get("n_devices") is not None:
                    rec.setdefault("n_devices", data["n_devices"])
                return rec
        return {k: v for k, v in data.items() if k != "tail"}
    return data


def _is_num(v):
    return isinstance(v, numbers.Real) and not isinstance(v, bool)


def diff_records(old, new):
    """List of per-field diff dicts (``STATUS_ORDER``-sorted):
    ``{field, old, new, rel_change, direction, rel_tol, status}``."""
    out = []
    for field in sorted(set(old) | set(new)):
        o, n = old.get(field), new.get(field)
        direction, rel_tol = threshold_for(field)
        row = {"field": field, "old": o, "new": n,
               "direction": direction, "rel_tol": rel_tol,
               "rel_change": None}
        if field not in old:
            row["status"] = "added"
        elif field not in new:
            row["status"] = "removed"
        elif not (_is_num(o) and _is_num(n)):
            row["status"] = "ok" if o == n else "changed"
        else:
            rel = (n - o) / abs(o) if o else (0.0 if n == o else
                                              float("inf"))
            row["rel_change"] = rel
            if direction is None:
                row["status"] = "info"
            elif direction == "zero":
                # optimum-at-zero signed metric: gate |new| vs |old|
                # with the tolerance as an ABSOLUTE band
                drift = abs(n) - abs(o)
                if drift > rel_tol:
                    row["status"] = "regressed"
                elif drift < -rel_tol:
                    row["status"] = "improved"
                else:
                    row["status"] = "ok"
            else:
                signed = rel if direction == "higher" else -rel
                if signed < -rel_tol:
                    row["status"] = "regressed"
                elif signed > rel_tol:
                    row["status"] = "improved"
                else:
                    row["status"] = "ok"
        out.append(row)
    out.sort(key=lambda r: (STATUS_ORDER.index(r["status"]), r["field"]))
    return out


def regressions(diffs):
    return [d for d in diffs if d["status"] == "regressed"]


def _fmt_val(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def format_diff(diffs, old_name="old", new_name="new", verbose=False):
    """Human-readable diff lines; ``verbose`` includes ok/info rows."""
    lines = [f"bench diff: {old_name} -> {new_name}"]
    shown = 0
    for d in diffs:
        if not verbose and d["status"] in ("ok", "info"):
            continue
        shown += 1
        rel = ("" if d["rel_change"] is None
               else f" ({d['rel_change']:+.1%})")
        if d["direction"] is None:
            gate = ""
        elif d["direction"] == "zero":
            gate = f" [zero-is-better, abs band {d['rel_tol']:g}]"
        else:
            gate = (f" [{d['direction']}-is-better, tol "
                    f"{d['rel_tol']:.0%}]")
        lines.append(f"  {d['status'].upper():<10} {d['field']}: "
                     f"{_fmt_val(d['old'])} -> {_fmt_val(d['new'])}"
                     f"{rel}{gate}")
    n_reg = len(regressions(diffs))
    if shown == 0:
        lines.append("  (no changes outside tolerance)")
    lines.append(f"  {len(diffs)} field(s) compared, {n_reg} regression(s)")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bench_diff",
        description="Diff two BENCH_r*.json artifacts with per-field "
                    "regression thresholds from bench_schema")
    parser.add_argument("artifacts", nargs="+",
                        help="two bench JSON files (or, with "
                             "--self-check, a whole sequence)")
    parser.add_argument("--self-check", action="store_true",
                        help="diff each consecutive pair; report "
                             "violations, always exit 0 (CI mode over "
                             "the checked-in history)")
    parser.add_argument("--no-fail", action="store_true",
                        help="exit 0 even on regressions")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the diff rows as JSON")
    parser.add_argument("--verbose", action="store_true",
                        help="include within-tolerance fields")
    args = parser.parse_args(argv)

    if args.self_check:
        if len(args.artifacts) < 2:
            print("error: --self-check needs at least two artifacts",
                  file=sys.stderr)
            return 2
        for old_path, new_path in zip(args.artifacts, args.artifacts[1:]):
            diffs = diff_records(load_bench_record(old_path),
                                 load_bench_record(new_path))
            print(format_diff(diffs, old_path, new_path,
                              verbose=args.verbose))
            print()
        return 0

    if len(args.artifacts) != 2:
        print("error: expected exactly two artifacts (or --self-check)",
              file=sys.stderr)
        return 2
    old_path, new_path = args.artifacts
    diffs = diff_records(load_bench_record(old_path),
                         load_bench_record(new_path))
    if args.as_json:
        json.dump(diffs, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print(format_diff(diffs, old_path, new_path, verbose=args.verbose))
    if regressions(diffs) and not args.no_fail:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
