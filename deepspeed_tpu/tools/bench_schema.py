"""Schema of the driver-bench JSON records (``bench.py``'s one line and
``__graft_entry__.dryrun_multichip``'s one line).

The standing measurement rule (ROADMAP) is that every README/PERF
headline quotes a driver artifact — which only works if the artifact's
fields are stable and auditable.  This module is the registry: every
field the drivers may emit, its type, and its unit, plus
:func:`validate_record` which the drivers run over their records before
printing (fail-soft: schema drift is reported to stderr, never allowed
to lose a measured record).

Three field families are pattern-based rather than enumerated:

- ``offload_<row>_*`` — one group per offload bench row (``gpt2_large``,
  ``gpt2_large_bf16``, ``gpt2_xl``, ...).  Since round 6 every row
  carries ``host_state_dtype`` and ``host_state_bytes_per_step`` so the
  reduced-precision wire-bytes claim is checkable from the JSON alone;
  since round 8 each adds ``comm_wire_bytes_per_step``.
- ``leg_<name>_*`` — one group per multichip-dryrun leg (``zero2``,
  ``pipe``, ``pipe_3d``, ...): per-leg status, losses, the dp=1
  parity-reference loss, and the compile-time comm receipts — the
  structured replacement for the old ``{n_devices, rc, ok, tail}``
  MULTICHIP blob (``tools/bench_diff.py --self-check`` gates the
  ``MULTICHIP_r0*.json`` history with these).
- ``*_exc`` / ``*_error`` — per-row failure strings (a secondary row
  failure must never lose the validated primary metric).
"""

import numbers
import re

# exact field name -> (type, unit/notes)
FIELDS = {
    "metric": (str, "primary metric name"),
    "value": (numbers.Real, "samples/s"),
    "unit": (str, "unit of value"),
    "vs_baseline": (numbers.Real, "ratio vs reference V100 baseline"),
    "model_tflops_per_sec": (numbers.Real, "TFLOP/s"),
    "mfu": (numbers.Real, "model-FLOPs utilisation, 0..1"),
    "chip_peak_tflops": (numbers.Real, "bf16 peak TFLOP/s"),
    "loss": (numbers.Real, "final step loss"),
    "batch": (numbers.Integral, "primary row batch size"),
    "dropout": (numbers.Real, "dropout probability"),
    "device": (str, "device_kind"),
    "error": (str, "primary-metric failure"),
    "seq512_batch": (numbers.Integral, ""),
    "seq512_samples_per_sec": (numbers.Real, "samples/s"),
    "seq512_vs_baseline": (numbers.Real, ""),
    "seq512_mfu": (numbers.Real, ""),
    "gpt2_medium_seq1024_samples_per_sec": (numbers.Real, "samples/s"),
    "gpt2_medium_tokens_per_sec": (numbers.Real, "tokens/s"),
    "gpt2_mfu": (numbers.Real, ""),
    "gpt2_batch": (numbers.Integral, ""),
    "sparse_attn_seq": (numbers.Integral, "sequence length"),
    "sparse_attn_dense_ms": (numbers.Real, "ms, min of repeats"),
    "sparse_attn_sparse_ms": (numbers.Real, "ms, min of repeats"),
    "sparse_attn_speedup_vs_dense": (numbers.Real, "ratio"),
    "sparse_attn_repeats": (numbers.Integral,
                            "interleaved timing repeats (min-aggregated)"),
    "offload_xl_note": (str, ""),
    "compile_cache_hits": (numbers.Integral, ""),
    "compile_cache_misses": (numbers.Integral, ""),
    "compile_seconds_cold": (numbers.Real, "s, cache-miss compile wall"),
    "compile_seconds_warm": (numbers.Real, "s, cache-hit retrieval wall"),
    "compile_programs": (numbers.Integral, ""),
    "compile_cache_dir": (str, ""),
    # memory receipts (round 7, profiling/memory): live watermark after
    # the primary row + the compiled train-step program's own
    # memory_analysis figures — "did the step fit, and by how much" is
    # checkable from the JSON alone
    "peak_hbm_bytes": (numbers.Integral,
                       "peak_bytes_in_use summed over local devices"),
    "predicted_temp_bytes": (numbers.Integral,
                             "train_step memory_analysis temp bytes"),
    # communication receipts (round 8, profiling/comm): the compiled
    # step program's collective count and predicted wire bytes from the
    # compile-time HLO walk — the static comm receipt next to the
    # memory one (dp=1 single-chip rows legitimately read 0)
    "comm_collectives_per_step": (numbers.Integral,
                                  "collective ops in the step program"),
    "comm_wire_bytes_per_step": (numbers.Integral,
                                 "predicted wire bytes per step"),
    # overlap receipts (round 11, profiling/overlap): the static
    # critical-path analysis' statement of which predicted wire seconds
    # the compiled schedules actually pay as latency — the metric the
    # overlapped-streaming work (ROADMAP item 2) must drive down
    "exposed_wire_seconds": (numbers.Real,
                             "predicted un-overlapped (exposed) wire "
                             "seconds per step"),
    "overlap_fraction": (numbers.Real,
                         "hidden/total wire seconds, 0..1 (1.0 = fully "
                         "hidden or no wire)"),
    # attribution receipts (round 13, profiling/attribution): the
    # reconciled step budget — the predicted step seconds the compiled
    # programs + declared streams + driver account for, and the
    # fraction of the MEASURED step the model cannot explain.  The
    # doctor CLI replays the same reconciliation offline
    "predicted_step_seconds": (numbers.Real,
                               "attribution budget: compute + exposed "
                               "wire + host stream + driver, s/step"),
    "step_unexplained_fraction": (numbers.Real,
                                  "(measured p50 - predicted)/measured "
                                  "(negative = model over-predicts)"),
    # program-verification receipt (round 10, profiling/verify +
    # tools/dslint/programs): unsuppressed DSP6xx violations over every
    # compiled engine program — donation aliases materialized,
    # collectives on the right mesh axes.  0 at HEAD; any regression
    # gates via bench_diff
    "dsp_violations": (numbers.Integral,
                       "ERROR-severity DSP6xx program-verifier findings "
                       "(gated at zero; heuristic warnings report via "
                       "dsp_warnings, which has no ratchet to need)"),
    "dsp_warnings": (numbers.Integral,
                     "warning-severity DSP6xx findings (informational, "
                     "never gated — no ratchet exists on this surface)"),
    "dsp_downgraded": (numbers.Integral,
                       "DSP602 downgraded verdicts (alias bytes "
                       "unverifiable: warm-cache/absent/partial)"),
    # sharding residency receipt (round 17, profiling/sharding +
    # DSS8xx): the compiled train step's MATERIALIZED per-device
    # parameter bytes from its entry-layout sharding annotations — the
    # bench half of ROADMAP item 2's parameter-memory ÷ dp criterion.
    # Gated lower-is-better: re-replicated parameters show here before
    # they OOM anything
    "param_bytes_per_device": (numbers.Integral,
                               "materialized per-device parameter "
                               "bytes (entry-layout ÷shard receipt)"),
    # ZeRO-2 bucketed-collective A/B row (round 14, bench.py
    # _measure_zero2_overlap via the fresh-subprocess harness):
    # overlap_comm on (the headline) vs off (the serialized control) on
    # a dp mesh, with both schedules' static exposed-wire receipts —
    # dryrun-marked on non-TPU backends (toy geometry on a virtual CPU
    # mesh proves the plumbing; the bench attachment proves the ms)
    "zero2_overlap_ms_per_step": (numbers.Real, "ms, overlap_comm on"),
    "zero2_serial_ms_per_step": (numbers.Real,
                                 "ms, serialized control (info)"),
    "zero2_overlap_exposed_wire_seconds": (numbers.Real,
                                           "declared-schedule exposure"),
    "zero2_serial_exposed_wire_seconds": (numbers.Real,
                                          "control exposure (info)"),
    "zero2_overlap_fraction": (numbers.Real, "hidden/total, 0..1"),
    "zero2_overlap_buckets": (numbers.Integral,
                              "reduce buckets in the schedule"),
    "zero2_overlap_dp": (numbers.Integral, "data-parallel degree"),
    "zero2_overlap_note": (str, ""),
    # multichip-dryrun record envelope (dryrun_multichip's one line;
    # legacy blobs keep n_devices/rc/ok/skipped readable)
    "multichip_schema_version": (numbers.Integral, ""),
    "n_devices": (numbers.Integral, "virtual device count"),
    "axes": (str, "mesh axes exercised"),
    "legs_ok": (numbers.Integral, "legs that passed"),
    "legs_failed": (numbers.Integral, "legs that failed"),
    "legs_skipped": (numbers.Integral, ""),
    "rc": (numbers.Integral, "legacy driver wrapper exit code"),
    "ok": (bool, "legacy driver wrapper flag"),
    "skipped": (bool, "legacy driver wrapper flag"),
    # fleet integrity receipt (round 15): seeded SDC faults the
    # integrity leg injected MINUS the ones the fingerprint consensus
    # caught — 0 is the receipt that nothing silent went undetected
    "integrity_violations": (numbers.Integral,
                             "seeded integrity faults left undetected"),
    # serving receipts (round 16, inference/engine via
    # examples/bench_serving.py): the continuous-batching serve's
    # latency/throughput record — every README serving headline quotes
    # these fields, and the dsp receipt pins the KV-cache donation
    "serving_requests": (numbers.Integral, "finished requests"),
    "serving_generated_tokens": (numbers.Integral, ""),
    "serving_decode_iterations": (numbers.Integral,
                                  "continuous-batch decode dispatches"),
    "serving_per_token_p50_seconds": (numbers.Real,
                                      "s, decode per-token latency"),
    "serving_per_token_p99_seconds": (numbers.Real,
                                      "s, tail (includes TTFT stalls)"),
    "serving_ttft_p50_seconds": (numbers.Real, "s, time to first token"),
    "serving_tokens_per_second_per_chip": (numbers.Real, "tokens/s/chip"),
    "serving_programs_compiled": (numbers.Integral,
                                  "compiled serve programs (bounded by "
                                  "len(prefill_buckets) + 1)"),
    "serving_dsp_violations": (numbers.Integral,
                               "DSP6xx errors over the serve programs "
                               "(gated at zero: the KV-cache donation "
                               "receipt)"),
    # serving memory receipts (round 17): the HBM receipt every
    # training row carries, via the same bench.memory_receipts() path
    # (decode-program temp bytes; pinned-host registry usually absent)
    "serving_peak_hbm_bytes": (numbers.Integral,
                               "peak_bytes_in_use summed over local "
                               "devices after the serve"),
    "serving_predicted_temp_bytes": (numbers.Integral,
                                     "serve_decode memory_analysis "
                                     "temp bytes"),
    "serving_host_buffer_bytes": (numbers.Integral,
                                  "pinned-host registry bytes (serving "
                                  "rows normally omit this)"),
    # serving sharding receipt (round 17, DSS8xx): decode-program
    # weights + paged KV residency per device
    "serving_param_bytes_per_device": (numbers.Integral,
                                       "materialized per-device weight "
                                       "bytes of the decode program"),
    # serving resilience receipts (round 18, inference/frontend via
    # examples/bench_serving.py): the self-healing plane's ledger —
    # requeues after replica death, sheds at the admission bound,
    # expired deadlines, and the worst-case re-serve latency
    "serving_requeued_requests": (numbers.Integral,
                                  "requests re-served after a replica "
                                  "death (exactly-once requeue)"),
    "serving_shed_requests": (numbers.Integral,
                              "submits refused at max_queue_depth"),
    "serving_deadline_expired": (numbers.Integral,
                                 "requests finished by deadline expiry"),
    "serving_recovery_latency_seconds": (numbers.Real,
                                         "worst replica-death -> last "
                                         "requeued-result latency"),
    # serving observability receipts (round 19,
    # inference/observability via engine.serving_receipt()): goodput
    # vs raw throughput, SLO attainment, and the efficiency gauges the
    # continuous-batching claim rests on
    "serving_goodput_tokens_per_second_per_chip": (
        numbers.Real, "tokens/s/chip counting only SLO-conformant "
        "tokens (raw throughput minus tail misses)"),
    "serving_slo_attainment": (numbers.Real,
                               "fraction of generated tokens within "
                               "the inference.slo targets"),
    "serving_batch_occupancy_mean": (numbers.Real,
                                     "mean active/max_batch_size over "
                                     "decode iterations"),
    "serving_kv_block_occupancy_peak": (numbers.Real,
                                        "allocator used-block high "
                                        "water / capacity"),
    "serving_padding_waste_fraction": (numbers.Real,
                                       "padded-prefill tokens wasted "
                                       "by bucket rounding"),
}

# multichip leg fields: leg_<name>_<field>
_LEG_FIELDS = {
    "status": str,                       # ok | failed | skipped
    "loss": numbers.Real,                # first-step loss
    "loss2": numbers.Real,               # post-update second-step loss
    "parity_ref_loss": numbers.Real,     # dp=1 reference, same batches
    "comm_collectives": numbers.Integral,
    "comm_payload_bytes": numbers.Integral,
    "comm_wire_bytes": numbers.Integral,
    # elastic leg (round 9): the kill-and-resize transition the leg
    # proved — world size before/after and the step the resized fleet
    # resumed from
    "resized_from": numbers.Integral,
    "resized_to": numbers.Integral,
    "resume_step": numbers.Integral,
    # program-verification receipt (round 10): DSP6xx violations over
    # the leg engine's compiled programs
    "dsp_violations": numbers.Integral,
    # sharding residency receipt (round 17, DSS8xx)
    "param_bytes_per_device": numbers.Integral,
    # stage-3 ÷dp receipt (round 20): the global parameter bytes the
    # per-device residency divides out of, and the shard divisor the
    # leg proved (== dp under zero_optimization.stage 3)
    "param_bytes_global": numbers.Integral,
    "shard_divisor": numbers.Integral,
    # overlap receipts (round 11)
    "exposed_wire_seconds": numbers.Real,
    "overlap_fraction": numbers.Real,
    # attribution receipts (round 13)
    "predicted_step_seconds": numbers.Real,
    "step_unexplained_fraction": numbers.Real,
    # onebit leg (round 14): the compressed step's wire bytes next to
    # the fp32 flat buffer and the dense-allreduce ratio (~1/32 — the
    # 1-bit claim as an asserted receipt, not prose)
    "compressed_wire_bytes": numbers.Integral,
    "flat_fp32_bytes": numbers.Integral,
    "compressed_wire_ratio": numbers.Real,
    # zero2_overlap leg (round 14): the serialized control's exposure
    # next to the leg's own exposed_wire_seconds (strictly lower,
    # asserted in the leg)
    "serial_exposed_wire_seconds": numbers.Real,
    # integrity leg (round 15): the aimed-recovery transition the leg
    # proved — which rank the fingerprint consensus indicted, the
    # consensus verdict that did it, and the fleet size the eviction
    # resize landed on
    "evicted_rank": numbers.Integral,
    "verdict": str,
    # serving leg (round 16): the 2-replica CPU-mesh continuous-batching
    # serve — request/token counts and greedy-decode parity receipts
    # (mismatches vs the naive full-forward reference, pinned at 0),
    # plus the latency fields shared with the top-level serving_* family
    "requests": numbers.Integral,
    "generated_tokens": numbers.Integral,
    "decode_iterations": numbers.Integral,
    "parity_mismatches": numbers.Integral,
    "per_token_p50_seconds": numbers.Real,
    "tokens_per_second_per_chip": numbers.Real,
    "programs_compiled": numbers.Integral,
    # serving_chaos leg (round 18): the in-process self-healing receipt
    # — requests re-served exactly-once after the seeded eviction, the
    # consensus verdicts that fired, and the completed-set size
    "requeued_requests": numbers.Integral,
    "integrity_violations": numbers.Integral,
    "completed_requests": numbers.Integral,
    "recovery_latency_seconds": numbers.Real,
    # serving observability receipts (round 19): the serving leg's
    # goodput/SLO/occupancy record, mirroring the top-level
    # serving_* observability family
    "goodput_tokens_per_second_per_chip": numbers.Real,
    "slo_attainment": numbers.Real,
    "batch_occupancy_mean": numbers.Real,
    "kv_block_occupancy_peak": numbers.Real,
    "padding_waste_fraction": numbers.Real,
    "error": str,
    "note": str,
}
_LEG_RE = re.compile(
    r"^leg_(?P<leg>[a-z0-9_]+?)_(?P<field>%s)$"
    % "|".join(sorted(_LEG_FIELDS, key=len, reverse=True)))

# offload row fields: offload_<row>_<field>
_OFFLOAD_ROW_FIELDS = {
    "ms_per_step": numbers.Real,
    "params_b": numbers.Real,
    # reduced-precision receipts (round 6): storage dtype and the wire
    # bytes one update moves for host state — "bf16 ≈ half the fp32
    # row" is asserted against these, not prose
    "host_state_dtype": str,
    "host_state_bytes_per_step": numbers.Integral,
    "host_groups": numbers.Integral,
    # memory receipts (round 7): per-row watermark + compile-time
    # prediction + pinned-host registry total
    "peak_hbm_bytes": numbers.Integral,
    "predicted_temp_bytes": numbers.Integral,
    "host_buffer_bytes": numbers.Integral,
    # comm receipts (round 8)
    "comm_collectives_per_step": numbers.Integral,
    "comm_wire_bytes_per_step": numbers.Integral,
    # program-verification receipt (round 10)
    "dsp_violations": numbers.Integral,
    # sharding residency receipt (round 17, DSS8xx)
    "param_bytes_per_device": numbers.Integral,
    # overlap receipts (round 11)
    "exposed_wire_seconds": numbers.Real,
    "overlap_fraction": numbers.Real,
    # attribution receipts (round 13)
    "predicted_step_seconds": numbers.Real,
    "step_unexplained_fraction": numbers.Real,
    "error": str,
    "note": str,
}
_OFFLOAD_RE = re.compile(
    r"^offload_(?P<row>[a-z0-9_]+?)_(?P<field>%s)$"
    % "|".join(sorted(_OFFLOAD_ROW_FIELDS, key=len, reverse=True)))
# per-row failure strings: `<row>_exc` (guarded-retry exceptions) and
# `<row>_error` (invalid-measurement reports, e.g. gpt2_error,
# seq512_error) — both carry prose, never metrics
_EXC_RE = re.compile(r"^[a-z0-9_]+_(exc|error)$")


# -- regression-gate thresholds (tools/bench_diff.py) -----------------------
#
# field -> (direction, rel_tol).  direction "higher" = bigger is better
# (throughput, MFU), "lower" = smaller is better (step time, bytes);
# a change against the direction by more than rel_tol of the old value
# is a REGRESSION.  Fields absent here (and (None, None) entries) are
# informational: diffed, never gated — loss wobbles, device strings,
# cold-compile walls that legitimately differ between cold/warm runs.
THRESHOLDS = {
    "value": ("higher", 0.05),
    "vs_baseline": ("higher", 0.05),
    "model_tflops_per_sec": ("higher", 0.05),
    "mfu": ("higher", 0.05),
    "batch": ("higher", 0.0),            # a downgraded-batch retry must show
    "seq512_batch": ("higher", 0.0),
    "gpt2_batch": ("higher", 0.0),
    "seq512_samples_per_sec": ("higher", 0.05),
    "seq512_vs_baseline": ("higher", 0.05),
    "seq512_mfu": ("higher", 0.05),
    "gpt2_medium_seq1024_samples_per_sec": ("higher", 0.05),
    "gpt2_medium_tokens_per_sec": ("higher", 0.05),
    "gpt2_mfu": ("higher", 0.05),
    "sparse_attn_speedup_vs_dense": ("higher", 0.10),
    "compile_seconds_warm": ("lower", 0.50),
    "peak_hbm_bytes": ("lower", 0.10),
    "predicted_temp_bytes": ("lower", 0.10),
    # a step program that starts moving substantially more wire bytes
    # is a sharding/collective regression even before it shows up in
    # step time (generous tol: XLA is free to re-split collectives)
    "comm_wire_bytes_per_step": ("lower", 0.25),
    # exposure must not creep back once overlap lands; the fraction is
    # gated loosely (model-derived, scheduler-version sensitive) and
    # the absolute exposed seconds generously for the same reason
    "exposed_wire_seconds": ("lower", 0.25),
    "overlap_fraction": ("higher", 0.10),
    # attribution quality is CI-ratcheted like exposure: a predicted
    # step that grows is a budget regression (generous tol: the figure
    # is roofline-table sensitive), and the unexplained fraction is a
    # SIGNED optimum-at-zero metric (negative = over-prediction), so it
    # gates on magnitude with an absolute band — direction "zero",
    # wide (measured-latency noisy; DSO705's baseline ratchet is the
    # tighter per-program gate)
    "predicted_step_seconds": ("lower", 0.25),
    "step_unexplained_fraction": ("zero", 0.25),
    # any new program-verifier violation is a gated regression (zero
    # tolerance: the receipt exists to pin this at 0)
    "dsp_violations": ("lower", 0.0),
    # resident parameter bytes per device must only shrink (sharding
    # landing) — growth past the dtype/padding band is re-replication
    # (the DSS801/DSS803 bug class on the bench surface)
    "param_bytes_per_device": ("lower", 0.10),
    # multichip: device-count or passing-leg shrinkage must show
    "n_devices": ("higher", 0.0),
    "legs_ok": ("higher", 0.0),
    "legs_failed": ("lower", 0.0),
    # any seeded integrity fault the consensus missed is a gated
    # regression (zero tolerance: the receipt exists to pin this at 0)
    "integrity_violations": ("lower", 0.0),
    # zero-2 bucketed-collective A/B (round 14): the overlapped row's
    # step time and exposure are the gated headline; the serialized
    # control rows are informational (they exist to be worse)
    "zero2_overlap_ms_per_step": ("lower", 0.25),
    "zero2_overlap_exposed_wire_seconds": ("lower", 0.25),
    "zero2_overlap_fraction": ("higher", 0.10),
    # serving bench (round 16): throughput gated like the training
    # headline; latency percentiles informational (single-run tails);
    # the donation receipt and the compile bound pinned exactly
    "serving_tokens_per_second_per_chip": ("higher", 0.25),
    "serving_programs_compiled": ("lower", 0.0),
    "serving_dsp_violations": ("lower", 0.0),
    # serving memory + residency receipts (round 17): gated like the
    # training rows' equivalents
    "serving_peak_hbm_bytes": ("lower", 0.10),
    "serving_predicted_temp_bytes": ("lower", 0.10),
    "serving_param_bytes_per_device": ("lower", 0.10),
    # serving resilience receipts (round 18): counters are
    # informational (they scale with the bench's injected faults, not
    # with code quality); the exactly-once property itself is gated in
    # the serving_chaos leg via parity_mismatches
    # serving observability (round 19): goodput is the gated headline
    # (same tol as raw serving throughput — a goodput drop is either a
    # throughput or a tail-latency regression); attainment and the
    # occupancy/waste gauges are informational (they move with bench
    # load shape, not code quality)
    "serving_goodput_tokens_per_second_per_chip": ("higher", 0.25),
}

# thresholds for the pattern-based leg_<name>_<field> family
_LEG_FIELD_THRESHOLDS = {
    "comm_wire_bytes": ("lower", 0.25),
    "dsp_violations": ("lower", 0.0),
    "param_bytes_per_device": ("lower", 0.10),
    # stage-3 ÷dp receipt (round 20): the divisor can only grow (a drop
    # back to 1 is the sharding silently un-landing); global bytes are
    # informational (they track the dryrun model, not code quality)
    "shard_divisor": ("higher", 0.0),
    "exposed_wire_seconds": ("lower", 0.25),
    "overlap_fraction": ("higher", 0.10),
    # informational since round 16: the dryrun legs' predicted step
    # seconds come from roofline tables evaluated on whatever CPU the
    # dryrun ran on, and history shows >25% run-to-run wobble with no
    # code change — a noise class, not a regression signal.  The
    # STRUCTURAL receipts stay gated (comm_wire_bytes, dsp_violations,
    # exposure); the top-level bench predicted_step_seconds (measured
    # on the bench box) keeps its gate too
    "predicted_step_seconds": (None, None),
    "step_unexplained_fraction": ("zero", 0.25),
    # serving leg (round 16): parity mismatches are the token-identical
    # receipt (pinned at zero); latency fields stay informational on
    # the virtual-CPU dryrun mesh
    "parity_mismatches": ("lower", 0.0),
    "requests": ("higher", 0.0),
    # serving_chaos leg (round 18): an undetected seeded fault is a
    # regression (the in-leg assert already pins the exact counts)
    "integrity_violations": ("lower", 0.0),
    # onebit compressed-path receipts (round 14): more wire (or a
    # grown ratio) = the compression is leaking dense collectives
    "compressed_wire_bytes": ("lower", 0.25),
    "compressed_wire_ratio": ("lower", 0.25),
    # serving observability (round 19): goodput gated like the
    # top-level field; occupancy/attainment/waste informational on the
    # virtual-CPU dryrun mesh
    "goodput_tokens_per_second_per_chip": ("higher", 0.25),
}

# thresholds for the pattern-based offload_<row>_<field> family
_OFFLOAD_FIELD_THRESHOLDS = {
    "ms_per_step": ("lower", 0.10),
    "host_state_bytes_per_step": ("lower", 0.01),
    "peak_hbm_bytes": ("lower", 0.10),
    "predicted_temp_bytes": ("lower", 0.10),
    "host_buffer_bytes": ("lower", 0.10),
    "comm_wire_bytes_per_step": ("lower", 0.25),
    "dsp_violations": ("lower", 0.0),
    "param_bytes_per_device": ("lower", 0.10),
    "exposed_wire_seconds": ("lower", 0.25),
    "overlap_fraction": ("higher", 0.10),
    "predicted_step_seconds": ("lower", 0.25),
    "step_unexplained_fraction": ("zero", 0.25),
}


def threshold_for(key):
    """(direction, rel_tol) for a record key; (None, None) =
    informational (never gated)."""
    if key in THRESHOLDS:
        return THRESHOLDS[key]
    m = _OFFLOAD_RE.match(key)
    if m:
        return _OFFLOAD_FIELD_THRESHOLDS.get(m.group("field"),
                                             (None, None))
    m = _LEG_RE.match(key)
    if m:
        return _LEG_FIELD_THRESHOLDS.get(m.group("field"), (None, None))
    return (None, None)


def field_type(key):
    """Expected python type for a record key, or None if unknown."""
    if key in FIELDS:
        return FIELDS[key][0]
    m = _OFFLOAD_RE.match(key)
    if m:
        return _OFFLOAD_ROW_FIELDS[m.group("field")]
    m = _LEG_RE.match(key)
    if m:
        return _LEG_FIELDS[m.group("field")]
    if _EXC_RE.match(key):
        return str
    return None


def validate_record(record):
    """Return a list of problem strings (empty = schema-clean).

    Booleans are rejected where numbers are expected (bool is an int
    subclass — a True smuggled into a metric field is a bug; the two
    declared-bool legacy wrapper flags are the only exception)."""
    problems = []
    for key, value in record.items():
        want = field_type(key)
        if want is None:
            problems.append(f"unknown bench field {key!r}")
            continue
        ok = isinstance(value, want) and not (
            want not in (str, bool) and isinstance(value, bool))
        if not ok:
            problems.append(
                f"bench field {key!r} expected {want.__name__}, got "
                f"{type(value).__name__} ({value!r})")
    return problems
