"""Block-sparse attention compute: gathered blockwise softmax(QKᵀ)V.

TPU-native replacement for the reference's Triton block-sparse kernels
(``ops/sparse_attention/matmul.py`` SDD/DSD/DDS, ``softmax.py``, and the
C++ LUT helper ``csrc/sparse_attention/utils.cpp``).  The reference builds
look-up tables mapping nonzero blocks to kernel work items; here the layout
is compiled *into* the program: for each (head, query-block) the active
key-block indices are gathered — padded to the per-layout maximum count so
shapes stay static — and attention runs as batched ``[block, block]``
matmuls over only those blocks.  Compute and memory scale with the number
of active blocks (O(s·w) instead of O(s²)), the matmuls are MXU-shaped, and
XLA fuses the mask/softmax chain; no dynamic shapes, no scalar loops.

Differentiable end-to-end (used in training); numerics are checked against
dense attention + expanded mask in ``tests/unit/test_sparse_attention.py``.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e9


def layout_gather_indices(layout):
    """Static per-(head, q-block) active key-block indices.

    Returns ``(indices, valid)`` with shapes ``[h, nb, kmax]``: ``indices``
    padded with 0, ``valid`` marking real entries.  This is the analog of
    the reference's Triton LUTs (``softmax.py:22``, ``matmul.py:27``) —
    computed host-side once per (layout, seq_len) and baked into the jitted
    computation as constants.
    """
    layout = np.asarray(layout)
    h, nb, _ = layout.shape
    counts = layout.sum(-1)
    kmax = max(1, int(counts.max()))
    indices = np.zeros((h, nb, kmax), np.int32)
    valid = np.zeros((h, nb, kmax), bool)
    for hi in range(h):
        for qi in range(nb):
            cols = np.nonzero(layout[hi, qi])[0]
            indices[hi, qi, :len(cols)] = cols
            valid[hi, qi, :len(cols)] = True
    return indices, valid


def block_sparse_attention(q, k, v, layout, causal=False,
                           key_padding_mask=None, attn_mask=None,
                           rpe=None, scale=None):
    """softmax((QKᵀ)·scale + masks)V restricted to a block layout.

    Args:
        q, k, v: ``[batch, seq, heads, head_dim]``.
        layout: ``[H, nb, nb]`` 0/1 (H == heads or 1, shared).
        causal: additionally mask within-block upper triangles
            ('unidirectional' layouts; the reference's Triton softmax does
            this via the layout plus per-block masking).
        key_padding_mask: additive ``[batch, seq]``; masked keys must use a
            large-but-FINITE negative (e.g. ``NEG_INF = -1e9``) — true
            ``-inf`` turns the softmax into NaN before the fully-masked-row
            guard can zero it.
        attn_mask: additive ``[seq, seq]`` (reference 'mul'/'add' modes
            collapse to additive finite -1e9 masks here).
        rpe: additive relative-position bias ``[heads, seq, seq]``.
        scale: defaults to 1/sqrt(head_dim).
    """
    b, s, h, d = q.shape
    layout = np.asarray(layout)
    if layout.shape[0] == 1 and h > 1:
        layout = np.broadcast_to(layout, (h,) + layout.shape[1:])
    assert layout.shape[0] == h, f"layout heads {layout.shape[0]} != {h}"
    nb = layout.shape[1]
    assert s % nb == 0, f"seq {s} not divisible into {nb} blocks"
    blk = s // nb
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    indices, valid = layout_gather_indices(layout)  # [h, nb, kmax]
    kmax = indices.shape[-1]
    indices_j = jnp.asarray(indices)

    # [b, s, h, d] -> [b, h, nb, blk, d]
    def to_blocks(x):
        return x.reshape(b, nb, blk, h, d).transpose(0, 3, 1, 2, 4)

    qb, kb, vb = to_blocks(q), to_blocks(k), to_blocks(v)

    # gather active key/value blocks per (head, q-block): [b, h, nb, kmax, blk, d]
    def gather_per_head(x_h, idx_h):
        return x_h[:, idx_h]  # [b, nb_k, blk, d] indexed by [nb, kmax]

    kg = jax.vmap(gather_per_head, in_axes=(1, 0), out_axes=1)(kb, indices_j)
    vg = jax.vmap(gather_per_head, in_axes=(1, 0), out_axes=1)(vb, indices_j)

    # scores over active blocks only: [b, h, nb, blk_q, kmax, blk_k]
    scores = jnp.einsum("bhnqd,bhnkcd->bhnqkc", qb, kg,
                        preferred_element_type=jnp.float32) * scale

    # element positions for masking
    qpos = (np.arange(nb)[:, None] * blk + np.arange(blk)[None, :])  # [nb, blk]
    kpos = indices[..., None] * blk + np.arange(blk)  # [h, nb, kmax, blk]

    mask = np.broadcast_to(valid[..., None], kpos.shape)  # [h, nb, kmax, blk]
    add_mask = jnp.where(jnp.asarray(mask), 0.0, NEG_INF)  # [h, nb, kmax, blk]
    add_mask = add_mask[None, :, :, None]  # [1, h, nb, 1, kmax, blk]
    if causal:
        cm = kpos[:, :, None] <= qpos[None, :, :, None, None]  # [h,nb,blk_q,kmax,blk]
        add_mask = add_mask + jnp.where(jnp.asarray(cm), 0.0, NEG_INF)[None]
    scores = scores + add_mask

    kpos_j = jnp.asarray(kpos)
    if key_padding_mask is not None:
        kpm = key_padding_mask.astype(jnp.float32)  # [b, s]
        scores = scores + kpm[:, kpos_j][:, :, :, None]  # [b,h,nb,1,kmax,blk]
    if attn_mask is not None:
        am = attn_mask.astype(jnp.float32)  # [s, s]
        scores = scores + am[jnp.asarray(qpos)[:, :, None, None], kpos_j[:, :, None]]
    if rpe is not None:
        rp = rpe.astype(jnp.float32)  # [h, s, s]
        hh = jnp.arange(h)[:, None, None, None, None]
        scores = scores + rp[hh, jnp.asarray(qpos)[None, :, :, None, None],
                             kpos_j[:, :, None]]

    # softmax over all active key elements (kmax*blk), fp32.  Rows with no
    # visible key (every entry at ~NEG_INF — fully-masked query, e.g. a
    # padding row) yield zero output instead of uniform-over-garbage; for
    # that detection to work, additive masks must be finite (use -1e9, not
    # -inf).
    flat = scores.reshape(b, h, nb, blk, kmax * blk)
    m = jnp.max(flat, axis=-1, keepdims=True)
    all_masked = m <= NEG_INF * 0.5
    e = jnp.exp(flat - jax.lax.stop_gradient(jnp.where(all_masked, 0.0, m)))
    e = jnp.where(all_masked, 0.0, e)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    probs = (e / jnp.maximum(denom, 1e-20)).reshape(scores.shape)

    ctx = jnp.einsum("bhnqkc,bhnkcd->bhnqd", probs.astype(v.dtype), vg)
    return ctx.transpose(0, 2, 3, 1, 4).reshape(b, s, h, d)
