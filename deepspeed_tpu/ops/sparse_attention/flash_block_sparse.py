"""Pallas block-sparse flash attention for TPU.

TPU-native analog of the reference's Triton block-sparse kernel stack
(``ops/sparse_attention/matmul.py`` SDD/DSD/DDS + ``softmax.py`` +
``trsrc/*.tr``, with the C++ LUT builder ``csrc/sparse_attention/
utils.cpp``).  The reference compiles look-up tables that map nonzero
layout blocks to kernel work items; here the same LUTs are built host-side
from the ``[H, nb, nb]`` layout and fed to the Mosaic kernel as
scalar-prefetch operands.  Round 5 made the schedule a flattened
WORK LIST (``build_work_luts``): the streaming grid dimension runs one
tick per ACTIVE (q block, k block) pair — a ragged per-row grid padded
every row to the densest row's count, so BigBird's global row (attends
everything) made every row pay a full-density sweep.  Each ``BlockSpec``
index map reads the job arrays to decide which Q and K/V blocks to DMA
next; softmax state opens/closes on first/last-of-row flag bits.
Compute and HBM traffic scale with the number of active blocks — O(s·w)
— while the inner loop is the flash-attention online softmax on
MXU-shaped ``[blk, blk]`` tiles (the dense flash kernel's recurrence,
``ops/transformer/flash_attention.py``, restricted to the layout).

Backward is a SINGLE fused pass over the same row-major work list: dq
accumulates per-row scratch; dk/dv accumulate into full-sequence [s, d]
fp32 VMEM scratch at each job's k-block offset (4 MB per buffer at
seq 16k/d 64), which deletes the transposed-LUT second pass and its
score/softmax recomputation entirely.

No in-kernel dropout (compose ``TransformerLayer``'s output dropout) and
no key-padding mask in v1 — the gather-based ``block_sparse.py`` remains
the fully-general reference implementation and the CPU path.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..transformer.flash_attention import (MAX_FLOOR, NEG_INF, _VMEM,
                                           _flatten_heads, _unflatten_heads,
                                           pltpu)


def build_block_luts(layout):
    """Host-side LUTs from a ``[H, nb, nb]`` 0/1 layout (the analog of the
    reference's ``make_lut``, ``softmax.py:22`` / ``matmul.py:27``).

    Returns ``(lut, cnt, tlut, tcnt)``:
      - ``lut[h, qb, t]``: t-th active key-block for query block qb
        (``cnt[h, qb]`` valid entries, zero-padded);
      - ``tlut[h, kb, t]``: t-th query block attending to key block kb
        (``tcnt[h, kb]`` valid entries) — the transposed layout, for dk/dv.
    """
    layout = np.asarray(layout) != 0
    h, nb, nb2 = layout.shape
    assert nb == nb2, f"layout must be square, got {layout.shape}"
    kmax = max(1, int(layout.sum(-1).max()))
    qmax = max(1, int(layout.sum(-2).max()))
    lut = np.zeros((h, nb, kmax), np.int32)
    cnt = np.zeros((h, nb), np.int32)
    tlut = np.zeros((h, nb, qmax), np.int32)
    tcnt = np.zeros((h, nb), np.int32)
    for hi in range(h):
        for qb in range(nb):
            cols = np.nonzero(layout[hi, qb])[0]
            lut[hi, qb, :len(cols)] = cols
            cnt[hi, qb] = len(cols)
        for kb in range(nb):
            rows = np.nonzero(layout[hi, :, kb])[0]
            tlut[hi, kb, :len(rows)] = rows
            tcnt[hi, kb] = len(rows)
    return lut, cnt, tlut, tcnt


def build_work_luts(layout):
    """Flattened WORK-LIST LUTs: one entry per ACTIVE (q block, k block)
    pair, row-major sorted, plus the k-major transpose for dk/dv.

    Why: the ragged-grid form pads every q row to ``kmax`` ticks, and one
    dense row poisons the whole grid — BigBird's global row attends ALL
    32 key blocks at seq 16k/blk 512 while regular rows attend ~6, so
    every row paid 32 ticks (26 masked).  Work-list ticks equal the
    number of active blocks exactly; the kernel walks jobs and opens/
    closes the softmax state on row-change flags (CSR-style, the same
    reason the reference's Triton kernels iterate ``lut`` rows of raw
    nonzero blocks, ``matmul.py:27``).

    Returns ``(jq, jk, fl)``, each ``[H, T]`` int32: ``jq/jk`` the job's
    q/k block, ``fl`` flag bits (1 = first job of its row, 2 = last job
    of its row, 4 = compute).  Rows with NO active blocks get one
    no-compute job (first|last) so their output window is still
    initialized (zero output, matching the gather reference).  Heads pad
    to a common T with no-op jobs repeating the last position (the
    output window stays put, nothing recomputes).  No transposed list:
    the fused single-pass backward accumulates dk/dv in full-sequence
    VMEM scratch, so the k-major walk no longer exists."""
    layout = np.asarray(layout) != 0
    H, nb, _ = layout.shape

    def one(mat):  # mat[qb, kb] -> row-major job list
        jobs = []
        for qb in range(nb):
            cols = np.nonzero(mat[qb])[0]
            if len(cols) == 0:
                jobs.append((qb, 0, 1 | 2))
            else:
                for t, c in enumerate(cols):
                    fl = 4 | (1 if t == 0 else 0) | (
                        2 if t == len(cols) - 1 else 0)
                    jobs.append((qb, int(c), fl))
        return jobs

    per_head = [one(layout[hi]) for hi in range(H)]
    T = max(len(x) for x in per_head)
    jq = np.zeros((H, T), np.int32)
    jk = np.zeros((H, T), np.int32)
    fl = np.zeros((H, T), np.int32)
    for hi, jobs in enumerate(per_head):
        for t, (q_, k_, fl_) in enumerate(jobs):
            jq[hi, t], jk[hi, t], fl[hi, t] = q_, k_, fl_
        for t in range(len(jobs), T):  # no-op padding
            jq[hi, t], jk[hi, t], fl[hi, t] = jobs[-1][0], jobs[-1][1], 0
    return jq, jk, fl


def _layout_head(i, heads, n_layout_heads):
    """Layout-head index for flat batch·head grid index ``i``."""
    if n_layout_heads == 1:
        return 0
    return jax.lax.rem(i, heads)


def build_super_luts(layout, G):
    """2-D aggregated LUTs: coarsen the layout into ``G×G`` super-tiles so
    the kernel streams MXU-efficient ``[G·blk, G·blk]`` tiles (the fix for
    sub-512 layout blocks starving the MXU: the reference's Triton kernels
    run 16-px blocks natively, but TPU tiles want ~512-wide dots, so a
    super-tile covers a G×G patch of layout blocks and a per-tile BITMASK
    — bit ``row_g·G + col_g`` — keeps masking at the original block
    granularity).  Work scales with SUPER-tile density at the dense
    kernel's per-tile efficiency.

    Returns ``(slut, scnt, smask, stlut, stcnt, stmask)``:
      - ``slut[h, sq, t]``: t-th active super key-column for super q-row
        ``sq`` (``scnt[h, sq]`` valid entries);
      - ``smask[h, sq, t]``: G·G bits of that super-tile's sub-blocks;
      - ``stlut/stcnt/stmask``: the transpose — active super q-rows per
        super key-column (for dk/dv), with the SAME bit convention.
    """
    layout = np.asarray(layout) != 0
    h, nb, nb2 = layout.shape
    assert nb == nb2 and nb % G == 0 and G * G <= 32
    ns = nb // G
    # [h, ns, G, ns, G] → per-super-tile G×G patch
    patch = layout.reshape(h, ns, G, ns, G)
    active = patch.any(axis=(2, 4))                  # [h, ns, ns]
    bitval = (1 << (np.arange(G)[:, None] * G
                    + np.arange(G)[None, :])).astype(np.int64)
    bits = (patch.transpose(0, 1, 3, 2, 4) * bitval).sum((-1, -2))  # [h,ns,ns]
    tmax = max(1, int(active.sum(-1).max()))
    qmax = max(1, int(active.sum(-2).max()))
    slut = np.zeros((h, ns, tmax), np.int32)
    scnt = np.zeros((h, ns), np.int32)
    smask = np.zeros((h, ns, tmax), np.int32)
    stlut = np.zeros((h, ns, qmax), np.int32)
    stcnt = np.zeros((h, ns), np.int32)
    stmask = np.zeros((h, ns, qmax), np.int32)
    for hi in range(h):
        for sq in range(ns):
            cols = np.nonzero(active[hi, sq])[0]
            slut[hi, sq, :len(cols)] = cols
            scnt[hi, sq] = len(cols)
            smask[hi, sq, :len(cols)] = bits[hi, sq, cols]
        for sk in range(ns):
            rows = np.nonzero(active[hi, :, sk])[0]
            stlut[hi, sk, :len(rows)] = rows
            stcnt[hi, sk] = len(rows)
            stmask[hi, sk, :len(rows)] = bits[hi, rows, sk]
    return slut, scnt, smask, stlut, stcnt, stmask


def _super_tile_mask(mask_val, G, blk):
    """[G·blk, G·blk] bool from the G·G-bit super-tile mask: element
    (r, c) active iff bit ``(r//blk)·G + (c//blk)`` is set.  Built from
    two BROADCAST shifts (a [n,1] row shift then a [1,n] column shift) —
    fewer full-tile VPU passes than materializing the 2-D bit index."""
    n = G * blk
    row_sh = (jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0) // blk) * G
    col_sh = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1) // blk
    shifted = jax.lax.shift_right_logical(
        jax.lax.shift_right_logical(jnp.full((n, 1), mask_val, jnp.int32),
                                    row_sh), col_sh)
    return shifted & 1 > 0


def _tile_scores(q_blk, k_blk, scale, causal, j, kb, blk):
    s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_idx = j * blk + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 0)
        k_idx = kb * blk + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 1)
        s = jnp.where(q_idx >= k_idx, s, NEG_INF)
    return s


def _job(jq_ref, jk_ref, fl_ref, lh, t):
    f = fl_ref[lh, t]
    return (jq_ref[lh, t], jk_ref[lh, t],
            (f & 1) != 0, (f & 2) != 0, (f & 4) != 0)


def _fwd_kernel(jq_ref, jk_ref, fl_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_sc, l_sc, acc_sc, *, scale, causal, heads, n_layout_heads,
                blk):
    """Work-list forward: grid tick t executes job t — one ACTIVE
    (q block, k block) tile.  Softmax state opens on the job's
    first-of-row flag and the output window closes on last-of-row."""
    i, t = pl.program_id(0), pl.program_id(1)
    lh = _layout_head(i, heads, n_layout_heads)
    j, kb, first, last, valid = _job(jq_ref, jk_ref, fl_ref, lh, t)

    @pl.when(first)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(valid)
    def _step():
        s = _tile_scores(q_ref[0], k_ref[0], scale, causal, j, kb, blk)
        m, l = m_sc[...], l_sc[...]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=1, keepdims=True)),
                            MAX_FLOOR)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_sc[...] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[...] = m_new
        acc_sc[...] = acc_sc[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last)
    def _finalize():
        # rows with no active key block (no-compute job, or causal-masked
        # away) produce zero output, matching the gather reference's guard
        l = l_sc[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_sc[...] + jnp.log(l_safe))[:, 0]


def _bwd_fused_kernel(jq_ref, jk_ref, fl_ref, q_ref, k_ref, v_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                      dq_sc, dk_sc, dv_sc, *, scale, causal, heads,
                      n_layout_heads, blk):
    """Single-pass backward: dq, dk AND dv from one score materialization
    per active tile.  dq accumulates per-row in a [blk, d] scratch (the
    row-major job order closes it on last-of-row); dk/dv accumulate into
    FULL-SEQUENCE [s, d] fp32 VMEM scratch at each job's k-block offset —
    at d=64 that is 4 MB per buffer even at seq 16k, comfortably inside
    VMEM, and it deletes the entire second backward pass (transposed-LUT
    dk/dv kernel) with its score/softmax/dp recomputation and K/V
    re-streaming.  Measured round 5: 1.95x -> ~3x vs dense at the BigBird
    seq-16k bench layout together with the work-list grid."""
    i, t = pl.program_id(0), pl.program_id(1)
    n_t = pl.num_programs(1)
    lh = _layout_head(i, heads, n_layout_heads)
    j, kb, first, last, valid = _job(jq_ref, jk_ref, fl_ref, lh, t)

    @pl.when(t == 0)
    def _zero_dkv():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    @pl.when(first)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(valid)
    def _step():
        s = _tile_scores(q_ref[0], k_ref[0], scale, causal, j, kb, blk)
        p = jnp.exp(s - lse_ref[0, 0][:, None])  # [blk_q, blk_k] fp32
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0][:, None])).astype(k_ref.dtype)
        dq_sc[...] = dq_sc[...] + jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dv_blk = jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_blk = jax.lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        off = kb * blk
        dk_sc[pl.ds(off, blk), :] = dk_sc[pl.ds(off, blk), :] + dk_blk
        dv_sc[pl.ds(off, blk), :] = dv_sc[pl.ds(off, blk), :] + dv_blk

    @pl.when(last)
    def _finalize_dq():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)

    @pl.when(t == n_t - 1)
    def _finalize_dkv():
        # s was scaled after the q·kᵀ dot, so the 1/√d factor lands on dk
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _agg_tile_scores(q_tile, k_tile, scale, mask_val, causal, sq, skb, G,
                     blk):
    """[G·blk, G·blk] scores with the super-tile bitmask (and causal)
    applied — inactive sub-blocks mask to -inf exactly like causal
    masking, so the online softmax recurrence is untouched.

    (Round-4 negative result: branching on ``mask_val == full`` with
    ``lax.cond`` to skip the bitmask select on fully-active super-tiles
    measured 5.4–6.8 ms vs 4.3–5.1 unbranched at s4096/blk128 — the
    Mosaic branch costs more than the mask work it skips, consistent
    with the dense kernel's masked/unmasked-split result.)"""
    s = jax.lax.dot_general(q_tile, k_tile, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    active = _super_tile_mask(mask_val, G, blk)
    if causal:
        n = G * blk
        q_idx = sq * n + jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
        k_idx = skb * n + jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
        active = jnp.logical_and(active, q_idx >= k_idx)
    return jnp.where(active, s, NEG_INF)


def _fwd_kernel_agg(slut_ref, scnt_ref, smask_ref, q_ref, k_ref, v_ref,
                    o_ref, lse_ref, m_sc, l_sc, acc_sc, *, scale, causal,
                    heads, n_layout_heads, blk, G):
    """Forward over 2-D super-tiles: both q and k tiles span G layout
    blocks ([G·blk, d] each) so every dot runs at the dense kernel's tile
    shape; the G·G-bit mask keeps the math at layout-block granularity."""
    i, sq, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_t = pl.num_programs(2)
    lh = _layout_head(i, heads, n_layout_heads)

    @pl.when(t == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(t < scnt_ref[lh, sq])
    def _step():
        skb = slut_ref[lh, sq, t]
        s = _agg_tile_scores(q_ref[0], k_ref[0], scale,
                             smask_ref[lh, sq, t], causal, sq, skb, G, blk)
        m, l = m_sc[...], l_sc[...]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=1, keepdims=True)),
                            MAX_FLOOR)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_sc[...] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[...] = m_new
        acc_sc[...] = acc_sc[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finalize():
        l = l_sc[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_sc[...] + jnp.log(l_safe))[:, 0]


def _bwd_dq_kernel_agg(slut_ref, scnt_ref, smask_ref, q_ref, k_ref, v_ref,
                       do_ref, lse_ref, delta_ref, dq_ref, dq_sc, *, scale,
                       causal, heads, n_layout_heads, blk, G):
    i, sq, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_t = pl.num_programs(2)
    lh = _layout_head(i, heads, n_layout_heads)

    @pl.when(t == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(t < scnt_ref[lh, sq])
    def _step():
        skb = slut_ref[lh, sq, t]
        s = _agg_tile_scores(q_ref[0], k_ref[0], scale,
                             smask_ref[lh, sq, t], causal, sq, skb, G, blk)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0][:, None])).astype(k_ref.dtype)
        dq_sc[...] = dq_sc[...] + jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finalize():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel_agg(stlut_ref, stcnt_ref, stmask_ref, q_ref, k_ref,
                        v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                        dk_sc, dv_sc, *, scale, causal, heads,
                        n_layout_heads, blk, G):
    """dk/dv: the k/v tiles are fixed per super key-column; super q-rows
    stream via the transposed LUT with the same bit convention."""
    i, sk, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_t = pl.num_programs(2)
    lh = _layout_head(i, heads, n_layout_heads)

    @pl.when(t == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    @pl.when(t < stcnt_ref[lh, sk])
    def _step():
        sqb = stlut_ref[lh, sk, t]
        s = _agg_tile_scores(q_ref[0], k_ref[0], scale,
                             stmask_ref[lh, sk, t], causal, sqb, sk, G, blk)
        p = jnp.exp(s - lse_ref[0, 0][:, None])  # [G·blk, G·blk]
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dv_sc[...] = dv_sc[...] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0][:, None])).astype(q_ref.dtype)
        dk_sc[...] = dk_sc[...] + jax.lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finalize():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _grid_params(interpret, ndims=3):
    if pltpu is None or interpret:
        return {}
    sem = ("parallel",) * (ndims - 1) + ("arbitrary",)
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=sem,
        vmem_limit_bytes=100 * 1024 * 1024)}


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _fbs_attention(q, k, v, jq, jk, fl, nb, causal, interpret):
    out, _ = _fbs_fwd(q, k, v, jq, jk, fl, nb, causal, interpret)
    return out


def _fbs_specs(h, H, blk, d):
    def iq(i, t, jq_r, jk_r, fl_r):
        return (i, jq_r[_layout_head(i, h, H), t], 0)

    def ik(i, t, jq_r, jk_r, fl_r):
        return (i, jk_r[_layout_head(i, h, H), t], 0)

    def iq_row(i, t, jq_r, jk_r, fl_r):
        return (i, 0, jq_r[_layout_head(i, h, H), t])

    return iq, ik, iq_row


def _fbs_fwd(q, k, v, jq, jk, fl, nb, causal, interpret):
    b, s, h, d = q.shape
    H, T = jq.shape
    blk = s // nb
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    bh = b * h
    iq, ik, iq_row = _fbs_specs(h, H, blk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               heads=h, n_layout_heads=H, blk=blk)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, T),
            in_specs=[
                pl.BlockSpec((1, blk, d), iq),
                pl.BlockSpec((1, blk, d), ik),
                pl.BlockSpec((1, blk, d), ik),
            ],
            out_specs=[
                pl.BlockSpec((1, blk, d), iq),
                pl.BlockSpec((1, 1, blk), iq_row),
            ],
            scratch_shapes=[
                _VMEM((blk, 1), jnp.float32),
                _VMEM((blk, 1), jnp.float32),
                _VMEM((blk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(interpret, ndims=2),
    )(jq, jk, fl, qf, kf, vf)
    outh = _unflatten_heads(out, b, h)
    return outh, (q, k, v, jq, jk, fl, outh, lse)


def _fbs_bwd(nb, causal, interpret, res, g):
    q, k, v, jq, jk, fl, out, lse = res
    b, s, h, d = q.shape
    # the fused backward's dk/dv accumulate in full-sequence fp32 VMEM
    # scratch: ~12·s·d bytes incl. outputs.  Fine through seq 32k/d 64
    # (measured) and ~64k, but past the ~100 MB scoped-VMEM budget the
    # kernel cannot compile — fail with guidance instead of a Mosaic
    # internal error (the gather-based block_sparse_attention has no such
    # ceiling)
    if 12 * s * d > 96 * 1024 * 1024 and not interpret:
        raise ValueError(
            f"flash_block_sparse_attention backward needs ~{12 * s * d >> 20}"
            f" MB of VMEM scratch at seq {s}, head_dim {d} (limit ~96 MB): "
            f"use the gather-based block_sparse_attention for this shape, "
            f"or shard the sequence (ring attention / the seq mesh axis)")
    H, T = jq.shape
    blk = s // nb
    scale = 1.0 / math.sqrt(d)
    bh = b * h

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    dof, of = _flatten_heads(g), _flatten_heads(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                    keepdims=True).transpose(0, 2, 1)  # [bh, 1, s]
    iq, ik, iq_row = _fbs_specs(h, H, blk, d)

    def whole(i, t, jq_r, jk_r, fl_r):
        return (i, 0, 0)

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          heads=h, n_layout_heads=H, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, T),
            in_specs=[
                pl.BlockSpec((1, blk, d), iq),
                pl.BlockSpec((1, blk, d), ik),
                pl.BlockSpec((1, blk, d), ik),
                pl.BlockSpec((1, blk, d), iq),
                pl.BlockSpec((1, 1, blk), iq_row),
                pl.BlockSpec((1, 1, blk), iq_row),
            ],
            out_specs=[
                pl.BlockSpec((1, blk, d), iq),
                pl.BlockSpec((1, s, d), whole),
                pl.BlockSpec((1, s, d), whole),
            ],
            scratch_shapes=[
                _VMEM((blk, d), jnp.float32),
                _VMEM((s, d), jnp.float32),
                _VMEM((s, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
        **_grid_params(interpret, ndims=2),
    )(jq, jk, fl, qf, kf, vf, dof, lse, delta)

    return (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
            _unflatten_heads(dv, b, h), None, None, None)


_fbs_attention.defvjp(_fbs_fwd, _fbs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11))
def _fbs_attention_agg(q, k, v, slut, scnt, smask, stlut, stcnt, stmask,
                       causal, interpret, G):
    out, _ = _fbs_fwd_agg(q, k, v, slut, scnt, smask, stlut, stcnt, stmask,
                          causal, interpret, G)
    return out


def _fbs_fwd_agg(q, k, v, slut, scnt, smask, stlut, stcnt, stmask, causal,
                 interpret, G):
    b, s, h, d = q.shape
    H, nsq, tmax = slut.shape
    blk = s // (nsq * G)
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    bh = b * h

    kernel = functools.partial(_fwd_kernel_agg, scale=scale, causal=causal,
                               heads=h, n_layout_heads=H, blk=blk, G=G)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, nsq, tmax),
            in_specs=[
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, sq, 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r:
                             (i, lut_r[_layout_head(i, h, H), sq, t], 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r:
                             (i, lut_r[_layout_head(i, h, H), sq, t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, sq, 0)),
                pl.BlockSpec((1, 1, G * blk),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, 0, sq)),
            ],
            scratch_shapes=[
                _VMEM((G * blk, 1), jnp.float32),
                _VMEM((G * blk, 1), jnp.float32),
                _VMEM((G * blk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(slut, scnt, smask, qf, kf, vf)
    outh = _unflatten_heads(out, b, h)
    return outh, (q, k, v, slut, scnt, smask, stlut, stcnt, stmask, outh, lse)


def _fbs_bwd_agg(causal, interpret, G, res, g):
    (q, k, v, slut, scnt, smask, stlut, stcnt, stmask, out, lse) = res
    b, s, h, d = q.shape
    H, nsq, tmax = slut.shape
    qmax = stlut.shape[-1]
    blk = s // (nsq * G)
    scale = 1.0 / math.sqrt(d)
    bh = b * h

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    dof, of = _flatten_heads(g), _flatten_heads(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                    keepdims=True).transpose(0, 2, 1)  # [bh, 1, s]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_agg, scale=scale, causal=causal,
                          heads=h, n_layout_heads=H, blk=blk, G=G),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, nsq, tmax),
            in_specs=[
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, sq, 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r:
                             (i, lut_r[_layout_head(i, h, H), sq, t], 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r:
                             (i, lut_r[_layout_head(i, h, H), sq, t], 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, sq, 0)),
                pl.BlockSpec((1, 1, G * blk),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, 0, sq)),
                pl.BlockSpec((1, 1, G * blk),
                             lambda i, sq, t, lut_r, cnt_r, msk_r: (i, 0, sq)),
            ],
            out_specs=pl.BlockSpec(
                (1, G * blk, d),
                lambda i, sq, t, lut_r, cnt_r, msk_r: (i, sq, 0)),
            scratch_shapes=[_VMEM((G * blk, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
        **_grid_params(interpret),
    )(slut, scnt, smask, qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_agg, scale=scale, causal=causal,
                          heads=h, n_layout_heads=H, blk=blk, G=G),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(bh, nsq, qmax),
            in_specs=[
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sk, t, lut_r, cnt_r, msk_r:
                             (i, lut_r[_layout_head(i, h, H), sk, t], 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sk, t, lut_r, cnt_r, msk_r: (i, sk, 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sk, t, lut_r, cnt_r, msk_r: (i, sk, 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sk, t, lut_r, cnt_r, msk_r:
                             (i, lut_r[_layout_head(i, h, H), sk, t], 0)),
                pl.BlockSpec((1, 1, G * blk),
                             lambda i, sk, t, lut_r, cnt_r, msk_r:
                             (i, 0, lut_r[_layout_head(i, h, H), sk, t])),
                pl.BlockSpec((1, 1, G * blk),
                             lambda i, sk, t, lut_r, cnt_r, msk_r:
                             (i, 0, lut_r[_layout_head(i, h, H), sk, t])),
            ],
            out_specs=[
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sk, t, lut_r, cnt_r, msk_r: (i, sk, 0)),
                pl.BlockSpec((1, G * blk, d),
                             lambda i, sk, t, lut_r, cnt_r, msk_r: (i, sk, 0)),
            ],
            scratch_shapes=[
                _VMEM((G * blk, d), jnp.float32),
                _VMEM((G * blk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(stlut, stcnt, stmask, qf, kf, vf, dof, lse, delta)

    return (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
            _unflatten_heads(dv, b, h), None, None, None, None, None, None)


_fbs_attention_agg.defvjp(_fbs_fwd_agg, _fbs_bwd_agg)


def _pick_q_agg(blk, nb, q_agg):
    """2-D aggregation factor: grow super-tiles toward the dense kernel's
    tuned 512 width, bounded by the layout (nb % G == 0) and the 32-bit
    per-tile mask (G·G <= 32 → G <= 5; 4 in practice).  Measured: blk 256
    runs best UNaggregated (the G=2 union overhead beats the tile-shape
    gain), so aggregation engages for blk <= 128 only."""
    if q_agg == "never":
        return 1
    if q_agg in ("auto", None):
        if blk > 128:
            return 1
        G = max(512 // blk, 1)
    else:
        # explicit factor: honored at ANY block size (ablations need it)
        G = int(q_agg)
    requested = G
    G = min(G, nb, 4)
    while G > 1 and nb % G != 0:
        G -= 1
    G = max(G, 1)
    if q_agg not in ("auto", None, "never") and G != requested:
        # an ablation must not silently measure a different kernel than
        # it asked for
        from ...utils.logging import logger

        logger.warning(
            "flash_block_sparse_attention: explicit q_agg=%s clamped to "
            "G=%d (bounds: nb=%d divisibility, mask budget G<=4)",
            q_agg, G, nb)
    return G


def flash_block_sparse_attention(q, k, v, layout, causal=False,
                                 interpret=False, q_agg="auto"):
    """Block-sparse flash attention on ``[b, s, h, d]`` inputs.

    ``layout`` is the ``[H, nb, nb]`` 0/1 block layout (H == heads, or 1 for
    a shared layout) produced by ``sparsity_config.make_layout``.

    Small layout blocks (the reference's Triton kernels run 16-px blocks;
    BERT-scale configs use 128) starve the MXU as bare [blk, blk] tiles —
    measured 0.76× vs dense at block 128 — so for ``blk < 512`` the kernel
    aggregates ``q_agg`` consecutive layout rows per q tile (512 sublanes,
    the dense kernel's tuned shape) and masks inactive (row, key-block)
    pairs via a per-tick bitmask; dk/dv aggregates key rows symmetrically.
    ``q_agg``: "auto" (default), "never", or an explicit factor.

    Requires the Mosaic PRNG-free feature set only; on CPU builds without
    ``jax.experimental.pallas.tpu``, use the gather-based
    ``block_sparse_attention`` instead.
    """
    assert pltpu is not None, (
        "flash_block_sparse_attention needs jax.experimental.pallas.tpu; "
        "use block_sparse_attention (gather-based) on CPU-only builds")
    b, s, h, d = q.shape
    layout = np.asarray(layout)
    nb = layout.shape[1]
    assert s % nb == 0, f"seq {s} not divisible into {nb} blocks"
    assert layout.shape[0] in (1, h), (
        f"layout heads {layout.shape[0]} incompatible with {h} heads")
    blk = s // nb
    G = _pick_q_agg(blk, nb, q_agg)
    if G > 1:
        luts = tuple(jnp.asarray(a) for a in build_super_luts(layout, G))
        return _fbs_attention_agg(q, k, v, *luts, bool(causal),
                                  bool(interpret), G)
    jq, jk, fl = build_work_luts(layout)
    return _fbs_attention(q, k, v, jnp.asarray(jq), jnp.asarray(jk),
                          jnp.asarray(fl), int(nb), bool(causal),
                          bool(interpret))
