"""Pallas block-sparse flash attention for TPU.

TPU-native analog of the reference's Triton block-sparse kernel stack
(``ops/sparse_attention/matmul.py`` SDD/DSD/DDS + ``softmax.py`` +
``trsrc/*.tr``, with the C++ LUT builder ``csrc/sparse_attention/
utils.cpp``).  The reference compiles look-up tables that map nonzero
layout blocks to kernel work items; here the same LUTs are built host-side
from the ``[H, nb, nb]`` layout and fed to the Mosaic kernel as
scalar-prefetch operands: the grid's streaming dimension runs over the
per-(head, q-block) ACTIVE key blocks only, and each ``BlockSpec`` index
map reads the LUT to decide which K/V block to DMA next.  Compute and HBM
traffic scale with the number of active blocks — O(s·w) — while the inner
loop is the flash-attention online softmax on MXU-shaped ``[blk, blk]``
tiles (the dense flash kernel's recurrence, ``ops/transformer/
flash_attention.py``, restricted to the layout).

Backward runs the standard flash recurrence with the same LUT trick; the
dk/dv kernel streams over a host-side TRANSPOSED LUT (for each key block,
the q-blocks that attend to it).

No in-kernel dropout (compose ``TransformerLayer``'s output dropout) and
no key-padding mask in v1 — the gather-based ``block_sparse.py`` remains
the fully-general reference implementation and the CPU path.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..transformer.flash_attention import (MAX_FLOOR, NEG_INF, _VMEM,
                                           _flatten_heads, _unflatten_heads,
                                           pltpu)


def build_block_luts(layout):
    """Host-side LUTs from a ``[H, nb, nb]`` 0/1 layout (the analog of the
    reference's ``make_lut``, ``softmax.py:22`` / ``matmul.py:27``).

    Returns ``(lut, cnt, tlut, tcnt)``:
      - ``lut[h, qb, t]``: t-th active key-block for query block qb
        (``cnt[h, qb]`` valid entries, zero-padded);
      - ``tlut[h, kb, t]``: t-th query block attending to key block kb
        (``tcnt[h, kb]`` valid entries) — the transposed layout, for dk/dv.
    """
    layout = np.asarray(layout) != 0
    h, nb, nb2 = layout.shape
    assert nb == nb2, f"layout must be square, got {layout.shape}"
    kmax = max(1, int(layout.sum(-1).max()))
    qmax = max(1, int(layout.sum(-2).max()))
    lut = np.zeros((h, nb, kmax), np.int32)
    cnt = np.zeros((h, nb), np.int32)
    tlut = np.zeros((h, nb, qmax), np.int32)
    tcnt = np.zeros((h, nb), np.int32)
    for hi in range(h):
        for qb in range(nb):
            cols = np.nonzero(layout[hi, qb])[0]
            lut[hi, qb, :len(cols)] = cols
            cnt[hi, qb] = len(cols)
        for kb in range(nb):
            rows = np.nonzero(layout[hi, :, kb])[0]
            tlut[hi, kb, :len(rows)] = rows
            tcnt[hi, kb] = len(rows)
    return lut, cnt, tlut, tcnt


def _layout_head(i, heads, n_layout_heads):
    """Layout-head index for flat batch·head grid index ``i``."""
    if n_layout_heads == 1:
        return 0
    return jax.lax.rem(i, heads)


def _tile_scores(q_blk, k_blk, scale, causal, j, kb, blk):
    s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_idx = j * blk + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 0)
        k_idx = kb * blk + jax.lax.broadcasted_iota(jnp.int32, (blk, blk), 1)
        s = jnp.where(q_idx >= k_idx, s, NEG_INF)
    return s


def _fwd_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_sc, l_sc, acc_sc, *, scale, causal, heads, n_layout_heads,
                blk):
    i, j, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_t = pl.num_programs(2)
    lh = _layout_head(i, heads, n_layout_heads)

    @pl.when(t == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    @pl.when(t < cnt_ref[lh, j])
    def _step():
        kb = lut_ref[lh, j, t]
        s = _tile_scores(q_ref[0], k_ref[0], scale, causal, j, kb, blk)
        m, l = m_sc[...], l_sc[...]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=1, keepdims=True)),
                            MAX_FLOOR)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_sc[...] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[...] = m_new
        acc_sc[...] = acc_sc[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finalize():
        # rows with no active key block (cnt == 0, or causal-masked away)
        # produce zero output, matching the gather reference's guard
        l = l_sc[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_sc[...] + jnp.log(l_safe))[:, 0]


def _bwd_dq_kernel(lut_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_sc, *, scale, causal, heads,
                   n_layout_heads, blk):
    i, j, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_t = pl.num_programs(2)
    lh = _layout_head(i, heads, n_layout_heads)

    @pl.when(t == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    @pl.when(t < cnt_ref[lh, j])
    def _step():
        kb = lut_ref[lh, j, t]
        s = _tile_scores(q_ref[0], k_ref[0], scale, causal, j, kb, blk)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0][:, None])).astype(k_ref.dtype)
        dq_sc[...] = dq_sc[...] + jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finalize():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(tlut_ref, tcnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal,
                    heads, n_layout_heads, blk):
    # grid (bh, k blocks, q slots): q streams via the transposed LUT
    i, kb, t = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_t = pl.num_programs(2)
    lh = _layout_head(i, heads, n_layout_heads)

    @pl.when(t == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    @pl.when(t < tcnt_ref[lh, kb])
    def _step():
        j = tlut_ref[lh, kb, t]
        s = _tile_scores(q_ref[0], k_ref[0], scale, causal, j, kb, blk)
        p = jnp.exp(s - lse_ref[0, 0][:, None])  # [blk_q, blk_k] fp32
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dv_sc[...] = dv_sc[...] + jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, 0][:, None])).astype(q_ref.dtype)
        dk_sc[...] = dk_sc[...] + jax.lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finalize():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _grid_params(interpret):
    if pltpu is None or interpret:
        return {}
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=100 * 1024 * 1024)}


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _fbs_attention(q, k, v, lut, cnt, tlut, tcnt, causal, interpret):
    out, _ = _fbs_fwd(q, k, v, lut, cnt, tlut, tcnt, causal, interpret)
    return out


def _fbs_fwd(q, k, v, lut, cnt, tlut, tcnt, causal, interpret):
    b, s, h, d = q.shape
    H, nb, kmax = lut.shape
    blk = s // nb
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    bh = b * h

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               heads=h, n_layout_heads=H, blk=blk)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nb, kmax),
            in_specs=[
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r: (i, j, 0)),
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r:
                             (i, lut_r[_layout_head(i, h, H), j, t], 0)),
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r:
                             (i, lut_r[_layout_head(i, h, H), j, t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r: (i, j, 0)),
                pl.BlockSpec((1, 1, blk), lambda i, j, t, lut_r, cnt_r: (i, 0, j)),
            ],
            scratch_shapes=[
                _VMEM((blk, 1), jnp.float32),
                _VMEM((blk, 1), jnp.float32),
                _VMEM((blk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(lut, cnt, qf, kf, vf)
    outh = _unflatten_heads(out, b, h)
    return outh, (q, k, v, lut, cnt, tlut, tcnt, outh, lse)


def _fbs_bwd(causal, interpret, res, g):
    q, k, v, lut, cnt, tlut, tcnt, out, lse = res
    b, s, h, d = q.shape
    H, nb, kmax = lut.shape
    qmax = tlut.shape[-1]
    blk = s // nb
    scale = 1.0 / math.sqrt(d)
    bh = b * h

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    dof, of = _flatten_heads(g), _flatten_heads(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                    keepdims=True).transpose(0, 2, 1)  # [bh, 1, s]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          heads=h, n_layout_heads=H, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nb, kmax),
            in_specs=[
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r: (i, j, 0)),
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r:
                             (i, lut_r[_layout_head(i, h, H), j, t], 0)),
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r:
                             (i, lut_r[_layout_head(i, h, H), j, t], 0)),
                pl.BlockSpec((1, blk, d), lambda i, j, t, lut_r, cnt_r: (i, j, 0)),
                pl.BlockSpec((1, 1, blk), lambda i, j, t, lut_r, cnt_r: (i, 0, j)),
                pl.BlockSpec((1, 1, blk), lambda i, j, t, lut_r, cnt_r: (i, 0, j)),
            ],
            out_specs=pl.BlockSpec((1, blk, d),
                                   lambda i, j, t, lut_r, cnt_r: (i, j, 0)),
            scratch_shapes=[_VMEM((blk, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
        **_grid_params(interpret),
    )(lut, cnt, qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          heads=h, n_layout_heads=H, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, nb, qmax),
            in_specs=[
                pl.BlockSpec((1, blk, d), lambda i, kb, t, tlut_r, tcnt_r:
                             (i, tlut_r[_layout_head(i, h, H), kb, t], 0)),
                pl.BlockSpec((1, blk, d), lambda i, kb, t, tlut_r, tcnt_r: (i, kb, 0)),
                pl.BlockSpec((1, blk, d), lambda i, kb, t, tlut_r, tcnt_r: (i, kb, 0)),
                pl.BlockSpec((1, blk, d), lambda i, kb, t, tlut_r, tcnt_r:
                             (i, tlut_r[_layout_head(i, h, H), kb, t], 0)),
                pl.BlockSpec((1, 1, blk), lambda i, kb, t, tlut_r, tcnt_r:
                             (i, 0, tlut_r[_layout_head(i, h, H), kb, t])),
                pl.BlockSpec((1, 1, blk), lambda i, kb, t, tlut_r, tcnt_r:
                             (i, 0, tlut_r[_layout_head(i, h, H), kb, t])),
            ],
            out_specs=[
                pl.BlockSpec((1, blk, d),
                             lambda i, kb, t, tlut_r, tcnt_r: (i, kb, 0)),
                pl.BlockSpec((1, blk, d),
                             lambda i, kb, t, tlut_r, tcnt_r: (i, kb, 0)),
            ],
            scratch_shapes=[
                _VMEM((blk, d), jnp.float32),
                _VMEM((blk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(tlut, tcnt, qf, kf, vf, dof, lse, delta)

    return (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
            _unflatten_heads(dv, b, h), None, None, None, None)


_fbs_attention.defvjp(_fbs_fwd, _fbs_bwd)


def flash_block_sparse_attention(q, k, v, layout, causal=False,
                                 interpret=False):
    """Block-sparse flash attention on ``[b, s, h, d]`` inputs.

    ``layout`` is the ``[H, nb, nb]`` 0/1 block layout (H == heads, or 1 for
    a shared layout) produced by ``sparsity_config.make_layout``.  Layout
    block size should be >= 128 for MXU efficiency (the reference's Triton
    kernels use 16/32/64 blocks; TPU tiles want 128 lanes).

    Requires the Mosaic PRNG-free feature set only; on CPU builds without
    ``jax.experimental.pallas.tpu``, use the gather-based
    ``block_sparse_attention`` instead.
    """
    assert pltpu is not None, (
        "flash_block_sparse_attention needs jax.experimental.pallas.tpu; "
        "use block_sparse_attention (gather-based) on CPU-only builds")
    b, s, h, d = q.shape
    layout = np.asarray(layout)
    nb = layout.shape[1]
    assert s % nb == 0, f"seq {s} not divisible into {nb} blocks"
    assert layout.shape[0] in (1, h), (
        f"layout heads {layout.shape[0]} incompatible with {h} heads")
    lut, cnt, tlut, tcnt = (jnp.asarray(a) for a in build_block_luts(layout))
    return _fbs_attention(q, k, v, lut, cnt, tlut, tcnt, bool(causal),
                          bool(interpret))
