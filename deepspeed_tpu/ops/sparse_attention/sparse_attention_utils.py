"""Helpers for adopting sparse attention in existing models.

Re-design of ``deepspeed/ops/sparse_attention/sparse_attention_utils.py``
(``SparseAttentionUtils``, reference ``:13-224``) for pytree-parameter
models: sequence padding/unpadding to block multiples and position-embedding
extension are tensor ops (ported); the HuggingFace-module surgery
(``replace_model_self_attention_with_sparse_self_attention``, reference
``:85-149``) maps to the framework's ``module_inject`` policy walker for
our functional models.
"""

import jax.numpy as jnp
import numpy as np


class SparseAttentionUtils:
    @staticmethod
    def extend_position_embedding(position_embedding, max_position):
        """Tile an existing ``[orig_max, hidden]`` position-embedding table
        up to ``max_position`` (reference ``:19-66``, which mutates HF
        model weights in place; here: returns the new table)."""
        orig_max, hidden = position_embedding.shape
        if max_position <= orig_max:
            return position_embedding[:max_position]
        reps = -(-max_position // orig_max)
        out = jnp.tile(jnp.asarray(position_embedding), (reps, 1))[:max_position]
        return out

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Bump a tokenizer's max length (reference ``:68-83``)."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def pad_to_block_size(block_size, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0,
                          model_embeddings=None):
        """Pad the sequence dimension to a multiple of ``block_size``
        (reference ``:151-208``).  Returns ``(pad_len, input_ids,
        attention_mask, token_type_ids, position_ids, inputs_embeds)``;
        padded attention-mask positions are 0 (masked out)."""
        if input_ids is not None:
            seq_len = input_ids.shape[1]
        else:
            seq_len = inputs_embeds.shape[1]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return (pad_len, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad2d(x, value):
            if x is None:
                return None
            return jnp.pad(jnp.asarray(x), ((0, 0), (0, pad_len)),
                           constant_values=value)

        if inputs_embeds is not None:
            batch = inputs_embeds.shape[0]
            pad_ids = jnp.full((batch, pad_len), pad_token_id, jnp.int32)
            assert model_embeddings is not None, (
                "padding inputs_embeds requires model_embeddings")
            pad_embeds = model_embeddings(pad_ids)
            inputs_embeds = jnp.concatenate(
                [jnp.asarray(inputs_embeds), pad_embeds], axis=1)
        input_ids = pad2d(input_ids, pad_token_id)
        position_ids = pad2d(position_ids, pad_token_id)
        attention_mask = pad2d(attention_mask, 0)
        token_type_ids = pad2d(token_type_ids, 0)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Drop padding added by :meth:`pad_to_block_size` (reference
        ``:210-224``)."""
        if pad_len > 0:
            return sequence_output[:, :-pad_len]
        return sequence_output

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            model, max_position, sparsity_config=None):
        """HF-module surgery is torch-specific; for this framework's
        functional models use ``deepspeed_tpu.module_inject`` policies
        (reference ``:85-149``)."""
        raise NotImplementedError(
            "use deepspeed_tpu.module_inject to swap attention cores in "
            "functional models")


def _np(x):
    return np.asarray(x)
