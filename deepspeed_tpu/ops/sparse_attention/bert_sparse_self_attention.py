"""BertSparseSelfAttention: BERT's self-attention with a sparse core.

Re-design of ``deepspeed/ops/sparse_attention/bert_sparse_self_attention.py``
(reference ``:9-79``) in the framework's functional-module style
(``init``/``apply`` over a param pytree): Q/K/V projections + a
:class:`SparseSelfAttention` core, returning the merged-head context.
"""

import jax
import jax.numpy as jnp

from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import FixedSparsityConfig


class BertSparseSelfAttention:
    def __init__(self, config, sparsity_config=None):
        """``config`` needs ``hidden_size`` and ``num_attention_heads``
        (a BertConfig works)."""
        if config.hidden_size % config.num_attention_heads != 0:
            raise ValueError(
                f"The hidden size ({config.hidden_size}) is not a multiple of "
                f"the number of attention heads ({config.num_attention_heads})")
        self.config = config
        self.num_attention_heads = config.num_attention_heads
        self.attention_head_size = config.hidden_size // config.num_attention_heads
        self.all_head_size = self.num_attention_heads * self.attention_head_size
        # 'mul' mode: apply()'s attention_mask contract is 1-keep/0-drop
        self.sparse_self_attention = SparseSelfAttention(
            sparsity_config or FixedSparsityConfig(
                num_heads=config.num_attention_heads),
            key_padding_mask_mode="mul")

    def init(self, rng):
        h = self.config.hidden_size
        ks = jax.random.split(rng, 3)
        init_range = getattr(self.config, "initializer_range", 0.02)

        def dense(k):
            return {"kernel": jax.random.normal(k, (h, self.all_head_size),
                                                jnp.float32) * init_range,
                    "bias": jnp.zeros((self.all_head_size,), jnp.float32)}

        return {"query": dense(ks[0]), "key": dense(ks[1]), "value": dense(ks[2])}

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_attention_heads,
                         self.attention_head_size).transpose(0, 2, 1, 3)

    def apply(self, params, hidden_states, attention_mask=None):
        """hidden_states ``[b, s, hidden]``; attention_mask ``[b, s]``
        multiplicative key-padding mask (1 keep / 0 drop)."""

        def proj(p, x):
            return x @ p["kernel"].astype(x.dtype) + p["bias"].astype(x.dtype)

        q = self._split_heads(proj(params["query"], hidden_states))
        k = self._split_heads(proj(params["key"], hidden_states))
        v = self._split_heads(proj(params["value"], hidden_states))
        ctx = self.sparse_self_attention(
            q, k, v, key_padding_mask=attention_mask)
        b, h, s, d = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(b, s, self.all_head_size)
