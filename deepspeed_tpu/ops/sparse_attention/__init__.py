from .bert_sparse_self_attention import BertSparseSelfAttention
from .block_sparse import block_sparse_attention, layout_gather_indices
from .flash_block_sparse import (build_block_luts,
                                 flash_block_sparse_attention)
from .sparse_attention_utils import SparseAttentionUtils
from .sparse_self_attention import SparseSelfAttention
from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig,
                              build_sparsity_config)
