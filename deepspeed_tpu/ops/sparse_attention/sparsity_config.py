"""Block-sparse attention layout configurations.

API-compatible re-implementation (numpy, vectorized) of the reference's
``deepspeed/ops/sparse_attention/sparsity_config.py`` layout family:
``SparsityConfig`` (``:9``), ``DenseSparsityConfig`` (``:63``),
``FixedSparsityConfig`` (``:94``), ``VariableSparsityConfig`` (``:243``),
``BigBirdSparsityConfig`` (``:421``), ``BSLongformerSparsityConfig``
(``:544``).  A layout is an int array ``[num_heads, num_blocks,
num_blocks]`` where ``layout[h, i, j] == 1`` means query block ``i`` of head
``h`` attends to key block ``j``.  Layouts are *static* (host-side numpy):
the TPU compute path (``block_sparse.py``) bakes them into the compiled
program as gather indices, the analog of the reference's Triton look-up
tables (``matmul.py:27``, ``softmax.py:22``).
"""

import random

import numpy as np


class SparsityConfig:
    """Base class: head count, block size, shared-vs-per-head layouts
    (reference ``sparsity_config.py:9-61``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        """Zeroed ``[num_heads, num_blocks, num_blocks]`` layout."""
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence Length, {seq_len}, needs to be dividable by "
                f"Block size {self.block}!")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        """Copy head 0's layout to every head when layouts are shared."""
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active — dense attention expressed in the block-sparse
    framework, for comparison (reference ``:63-91``)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern from `Generative Modeling with Sparse Transformers`
    (arXiv:1904.10509), as customized by the reference (``:94-240``): local
    windows of ``num_local_blocks`` plus per-window global representative
    blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"Number of blocks in a local window, {num_local_blocks}, "
                f"must be dividable by number of global blocks, "
                f"{num_global_blocks}!")
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                'only "uni/bi-directional" attentions are supported for now!')
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                'only "bi-directional" attentions can support horizontal '
                "global attention!")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "Number of different layouts cannot be more than one when "
                "you have set a single layout for all heads! Set "
                "different_layout_per_head to True.")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"Number of layout versions (num_different_global_patterns), "
                f"{num_different_global_patterns}, cannot be larger than "
                f"number of local window blocks divided by number of global "
                f"blocks, {num_local_blocks // num_global_blocks}!")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        """Dense (or lower-triangular, if unidirectional) blocks within each
        ``num_local_blocks`` window."""
        num_blocks = layout.shape[1]
        uni = self.attention == "unidirectional"
        for start in range(0, num_blocks, self.num_local_blocks):
            end = min(start + self.num_local_blocks, num_blocks)
            w = end - start
            win = np.tril(np.ones((w, w), np.int64)) if uni else np.ones((w, w), np.int64)
            layout[h, start:end, start:end] |= win
        return layout

    def set_global_layout(self, h, layout):
        """Per-window global representative block columns (and rows when
        ``horizontal_global_attention``); heads rotate which block of the
        window is global when layouts differ per head."""
        num_blocks = layout.shape[1]
        first = self.num_local_blocks - (
            1 + h % self.num_different_global_patterns) * self.num_global_blocks
        end = num_blocks - (num_blocks % self.num_local_blocks)
        for i in range(first, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        if end < num_blocks:  # short last window
            start = min(end + first, num_blocks - self.num_global_blocks)
            stop = start + self.num_global_blocks
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:stop] = 1
            if self.horizontal_global_attention:
                layout[h, start:stop, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed pattern generalized with random blocks, variable-size local
    windows, and explicit global block indices (reference ``:243-418``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, "
                    f"{len(self.global_block_indices)}, must be same as global "
                    f"block end indices length, {len(global_block_end_indices)}!")
            for start_idx, end_idx in zip(self.global_block_indices,
                                          global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be "
                        f"smaller than global block end index, {end_idx}!")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                'only "uni/bi-directional" attentions are supported for now!')
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                'only "bi-directional" attentions can support horizontal '
                "global attention!")
        self.horizontal_global_attention = horizontal_global_attention

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks, {self.num_random_blocks}, must be "
                f"smaller than overal number of blocks in a row, {num_blocks}!")
        for row in range(num_blocks):
            cols = random.sample(range(num_blocks), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        num_blocks = layout.shape[1]
        uni = self.attention == "unidirectional"

        def fill(start, end):
            w = end - start
            if w <= 0:
                return
            win = np.tril(np.ones((w, w), np.int64)) if uni else np.ones((w, w), np.int64)
            layout[h, start:end, start:end] |= win

        start = 0
        size = self.local_window_blocks[-1]
        for size in self.local_window_blocks:
            fill(start, min(start + size, num_blocks))
            start += size
        for i in range(start, num_blocks, size):  # remaining windows reuse last size
            fill(i, min(i + size, num_blocks))
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start_idx, end_idx in spans:
            if start_idx >= num_blocks:
                continue
            end_idx = min(end_idx, num_blocks)
            if self.horizontal_global_attention:
                layout[h, start_idx:end_idx, :] = 1
            first_row = 0 if self.attention == "bidirectional" else start_idx
            layout[h, first_row:, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_local_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird pattern (arXiv:2007.14062): random + sliding window + ITC
    global blocks at the start of the sequence (reference ``:421-541``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def set_random_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks, {self.num_random_blocks}, must be "
                f"smaller than overal number of blocks in a row, {num_blocks}!")
        for row in range(num_blocks):
            cols = random.sample(range(num_blocks), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks, "
                f"{self.num_sliding_window_blocks}, must be smaller than "
                f"overal number of blocks in a row, {num_blocks}!")
        w = self.num_sliding_window_blocks // 2
        rows = np.arange(num_blocks)[:, None]
        cols = np.arange(num_blocks)[None, :]
        layout[h] |= (np.abs(rows - cols) <= w).astype(np.int64)
        return layout

    def set_global_layout_itc(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_global_blocks:
            raise ValueError(
                f"Number of global blocks, {self.num_global_blocks}, must be "
                f"smaller than overal number of blocks in a row, {num_blocks}!")
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_random_layout(h, layout)
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (arXiv:2004.05150): sliding window + explicit
    symmetric global blocks (reference ``:544-663``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, "
                    f"{len(self.global_block_indices)}, must be same as global "
                    f"block end indices length, {len(global_block_end_indices)}!")
            for start_idx, end_idx in zip(self.global_block_indices,
                                          global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be "
                        f"smaller than global block end index, {end_idx}!")
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks, "
                f"{self.num_sliding_window_blocks}, must be smaller than "
                f"overal number of blocks in a row, {num_blocks}!")
        w = self.num_sliding_window_blocks // 2
        rows = np.arange(num_blocks)[:, None]
        cols = np.arange(num_blocks)[None, :]
        layout[h] |= (np.abs(rows - cols) <= w).astype(np.int64)
        return layout

    def set_global_layout(self, h, layout):
        num_blocks = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start_idx, end_idx in spans:
            if start_idx >= num_blocks:
                continue
            end_idx = min(end_idx, num_blocks)
            layout[h, start_idx:end_idx, :] = 1
            layout[h, :, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self.set_sliding_window_layout(h, layout)
            layout = self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


def build_sparsity_config(sparse_attention_dict, num_heads):
    """Parsed ``sparse_attention`` config section → SparsityConfig instance.

    This is how the json config's sparse-attention subsection (reference
    ``config.py:192-360``; the bing_bert flow hands it to
    ``SparseSelfAttention``) becomes a live layout object: the ``mode`` key
    selects the class, every other key is a constructor kwarg.
    """
    modes = {
        "dense": DenseSparsityConfig,
        "fixed": FixedSparsityConfig,
        "variable": VariableSparsityConfig,
        "bigbird": BigBirdSparsityConfig,
        "bslongformer": BSLongformerSparsityConfig,
    }
    kwargs = dict(sparse_attention_dict)
    mode = kwargs.pop("mode", "fixed")
    if mode not in modes:
        raise ValueError(f"unknown sparse attention mode {mode!r}; "
                         f"expected one of {sorted(modes)}")
    return modes[mode](num_heads=num_heads, **kwargs)
