"""SparseSelfAttention: layout-driven sparse softmax(QKᵀ)V module.

Re-design of ``deepspeed/ops/sparse_attention/sparse_self_attention.py``
(``SparseSelfAttention``, reference ``:14-152``).  Same contract: inputs
``[batch, heads, seq, head_dim]``, optional additive/multiplicative key
padding and attention masks, relative position embedding; output a dense
context tensor.  The Triton SDD/softmax/DSD kernel chain (``get_ops``,
reference ``:66-87``) is replaced by the gathered block-sparse computation
in ``block_sparse.py``; layouts (and their gather LUTs) are cached per
sequence length exactly like the reference's ``master_layout`` slicing
(``:51-64``).
"""

import jax.numpy as jnp

from .block_sparse import NEG_INF, block_sparse_attention
from .sparsity_config import FixedSparsityConfig, SparsityConfig


class SparseSelfAttention:
    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        assert isinstance(self.sparsity_config, SparsityConfig)
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError(f"bad key_padding_mask_mode {key_padding_mask_mode}")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError(f"bad attn_mask_mode {attn_mask_mode}")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self._master_layout = None
        self._layout_cache = {}

    def get_layout(self, seq_len):
        """Layout for ``seq_len``, sliced from a lazily-built master layout
        (reference ``:51-64``)."""
        if seq_len in self._layout_cache:
            return self._layout_cache[seq_len]
        if self._master_layout is None:
            self._master_layout = self.sparsity_config.make_layout(
                self.max_seq_length)
        if seq_len % self.sparsity_config.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be a multiple of block "
                f"{self.sparsity_config.block}")
        num_blocks = seq_len // self.sparsity_config.block
        if num_blocks > self._master_layout.shape[1]:
            raise ValueError(
                f"seq_len {seq_len} exceeds max_seq_length {self.max_seq_length}")
        layout = self._master_layout[:, :num_blocks, :num_blocks]
        self._layout_cache[seq_len] = layout
        return layout

    def _additive(self, mask, mode):
        """'mul' masks (1 keep / 0 drop) → additive -inf form."""
        mask = jnp.asarray(mask)
        if mode == "mul":
            return jnp.where(mask != 0, 0.0, NEG_INF)
        return mask.astype(jnp.float32)

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        """query/key/value: ``[batch, heads, seq, head_dim]`` (the
        reference's post-``transpose_for_scores`` shape)."""
        if query.shape != key.shape or key.shape != value.shape:
            raise NotImplementedError("only self-attention is supported for now")
        b, h, s, d = query.shape
        layout = self.get_layout(s)

        if key_padding_mask is not None:
            key_padding_mask = jnp.asarray(key_padding_mask).reshape(b, s)
            key_padding_mask = self._additive(key_padding_mask,
                                              self.key_padding_mask_mode)
        if attn_mask is not None:
            attn_mask = jnp.asarray(attn_mask)
            attn_mask = attn_mask.reshape(attn_mask.shape[-2:])
            attn_mask = self._additive(attn_mask, self.attn_mask_mode)

        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        # block_sparse_attention takes [b, s, h, d]
        ctx = block_sparse_attention(
            query.transpose(0, 2, 1, 3), key.transpose(0, 2, 1, 3),
            value.transpose(0, 2, 1, 3), layout, causal=causal,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask, rpe=rpe)
        return ctx.transpose(0, 2, 1, 3)

    forward = __call__
