"""Shared helpers for flat-parameter-space optimizer ops.

The reference implements optimizers as chunked multi-tensor CUDA kernels
(``csrc/adam/multi_tensor_adam.cu``, ``csrc/lamb/fused_lamb_cuda_kernel.cu``)
to amortize launch overhead.  On TPU the analog is a *flat parameter space*:
all parameters live in one fp32 buffer, the optimizer update is one fused
elementwise XLA computation over it, and ZeRO sharding is an even split of
the buffer along the ``data`` mesh axis.

TPU layout note: the buffer is 2-D ``(rows, LANES=1024)``, **not** 1-D.
XLA TPU factorizes large 1-D arrays into pathological 2-D layouts (e.g.
``[N/2, 2]`` whose lane dim pads 2→128, a 64× memory blow-up observed with
BERT-large); a 1024-lane 2-D buffer tiles natively.  Each tensor starts on
a row boundary so per-tensor views are contiguous row ranges, and the row
count is padded to the DP degree so shards split evenly — the analog of the
reference's comm-interval alignment (``stage1.py:32-103``).
"""

from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

LANES = 1024


class Segments(NamedTuple):
    """Static map from flat-buffer rows back to parameter tensors."""

    row_offsets: Tuple[int, ...]  # first row of each tensor
    row_counts: Tuple[int, ...]   # rows occupied by each tensor
    sizes: Tuple[int, ...]        # true element count of each tensor
    rows: int                     # total rows including padding

    @property
    def num_segments(self):
        return len(self.sizes)

    @property
    def total(self):
        """Total element capacity of the buffer."""
        return self.rows * LANES

    @property
    def shape(self):
        return (self.rows, LANES)

    def segment_ids(self) -> np.ndarray:
        """i32[rows, LANES] mapping each element to its tensor index; padding
        (inter-tensor row tails + trailing rows) maps to ``num_segments``."""
        ids = np.full((self.rows, LANES), self.num_segments, dtype=np.int32)
        flat = ids.reshape(-1)
        for i, (ro, n) in enumerate(zip(self.row_offsets, self.sizes)):
            start = ro * LANES
            flat[start:start + n] = i
        return ids

    def row_segment_ids(self) -> np.ndarray:
        """i32[rows] mapping each ROW to its tensor index (rows are
        segment-pure by construction); trailing pad rows map to
        ``num_segments``.  The row-granular analog of ``segment_ids`` —
        1/LANES the size, enough for any per-tensor scaling that can
        tolerate intra-row padding picking up its tensor's value."""
        ids = np.full((self.rows,), self.num_segments, dtype=np.int32)
        for i, (ro, rc) in enumerate(zip(self.row_offsets, self.row_counts)):
            ids[ro:ro + rc] = i
        return ids


def build_segments(sizes: List[int], pad_to: int = 1) -> Segments:
    """Row-aligned segment layout; ``pad_to`` pads total rows to a multiple
    (the DP shard count)."""
    row_offsets = []
    row_counts = []
    row = 0
    for n in sizes:
        rc = -(-n // LANES)
        row_offsets.append(row)
        row_counts.append(rc)
        row += rc
    if pad_to > 1 and row % pad_to != 0:
        row += pad_to - (row % pad_to)
    return Segments(row_offsets=tuple(row_offsets), row_counts=tuple(row_counts),
                    sizes=tuple(sizes), rows=row)


def segment_l2_norms(flat: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int):
    """Per-tensor L2 norms of the (rows, LANES) buffer in one scatter-add.

    Generic path for arbitrary id layouts.  NOTE: an element-level scatter
    over the whole flat buffer is catastrophically slow on TPU (XLA
    serializes large variable-index scatters — measured 0.86 samples/s on
    GPT-2-medium LAMB vs 30+ with the row path below); flat-space callers
    should use :func:`segment_l2_norms_rows`."""
    sq = (jnp.asarray(flat, jnp.float32) ** 2).reshape(-1)
    ids = segment_ids.reshape(-1)
    sums = jnp.zeros((num_segments + 1,), jnp.float32).at[ids].add(sq)
    return jnp.sqrt(sums[:num_segments])


def segment_l2_norms_rows(flat: jnp.ndarray, segments) -> jnp.ndarray:
    """Per-tensor L2 norms exploiting the flat layout's ROW alignment
    (``build_segments``: every tensor owns whole rows; intra-row tail
    padding is zero in params, grads, and updates).  One lane-axis
    reduction then a static slice+sum per tensor — no scatter anywhere,
    one sweep of HBM.

    The per-tensor Python loop emits one slice+reduce pair of HLO per
    tensor; at very high tensor counts (thousands of leaves) that inflates
    program size and compile time.  If that bites, a single
    ``jax.ops.segment_sum`` over ``row_sq`` keyed by row-granular segment
    ids stays scatter-light while emitting O(1) HLO."""
    row_sq = jnp.sum(jnp.asarray(flat, jnp.float32) ** 2, axis=1)
    sums = [jnp.sum(row_sq[ro:ro + rc])
            for ro, rc in zip(segments.row_offsets, segments.row_counts)]
    return jnp.sqrt(jnp.stack(sums))


def random_keep(rng, shape, rate):
    """Inverted-dropout keep mask + scale, generated as ONE random byte per
    element.

    ``jax.random.bernoulli`` draws an fp32 uniform per element — 4 bytes of
    RNG output plus an fp32 compare, which on TPU made dropout cost ~30% of
    a BERT-large train step (the reference hides the same cost inside its
    fused kernels' cuRAND path, ``csrc/transformer/dropout_kernels.cu``).
    Here the keep test is an 8-bit threshold compare: the drop rate is
    quantized to ``round(rate * 256) / 256`` (within 1/512 of the request)
    and the returned scale is ``256 / (256 - thresh)`` — *exactly* unbiased
    for the quantized rate, i.e. ``E[keep * scale] == 1``.

    Returns ``(keep_mask_bool, scale_float)``.
    """
    thresh = min(255, max(1, int(round(float(rate) * 256.0))))
    bits = jax.random.bits(rng, shape, dtype=jnp.uint8)
    return bits >= jnp.uint8(thresh), 256.0 / (256 - thresh)
