"""Shared helpers for flat-parameter-space optimizer ops.

The reference implements optimizers as chunked multi-tensor CUDA kernels
(``csrc/adam/multi_tensor_adam.cu``, ``csrc/lamb/fused_lamb_cuda_kernel.cu``)
to amortize launch overhead.  On TPU the analog is a *flat parameter space*:
all parameters live in one 1-D fp32 buffer (padded to the data-parallel
degree), the optimizer update is one fused elementwise XLA computation over
it, and ZeRO sharding is a trivial even split of the buffer along the
``data`` mesh axis.  Per-tensor structure (needed by LAMB trust ratios and
checkpoint I/O) is carried by a static ``Segments`` descriptor.
"""

from typing import List, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np


class Segments(NamedTuple):
    """Static map from flat-buffer offsets back to parameter tensors."""

    offsets: Tuple[int, ...]   # start offset of each tensor
    sizes: Tuple[int, ...]     # element count of each tensor
    total: int                 # flat length including padding

    @property
    def num_segments(self):
        return len(self.sizes)

    def segment_ids(self) -> np.ndarray:
        """i32[total] mapping each flat element to its tensor index; padding
        elements map to an extra trailing segment id."""
        ids = np.full((self.total,), self.num_segments, dtype=np.int32)
        for i, (o, n) in enumerate(zip(self.offsets, self.sizes)):
            ids[o:o + n] = i
        return ids


def build_segments(sizes: List[int], pad_to: int = 1) -> Segments:
    offsets = []
    off = 0
    for n in sizes:
        offsets.append(off)
        off += n
    total = off
    if pad_to > 1 and total % pad_to != 0:
        total += pad_to - (total % pad_to)
    return Segments(offsets=tuple(offsets), sizes=tuple(sizes), total=total)


def segment_l2_norms(flat: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int):
    """Per-tensor L2 norms of a flat buffer in one scatter-add pass."""
    sq = jnp.asarray(flat, jnp.float32) ** 2
    sums = jnp.zeros((num_segments + 1,), jnp.float32).at[segment_ids].add(sq)
    return jnp.sqrt(sums[:num_segments])
