"""Fused LAMB over the flat parameter space.

TPU-native equivalent of the reference's 3-phase LAMB CUDA kernel
(``csrc/lamb/fused_lamb_cuda_kernel.cu:186-312``; Python wrapper
``deepspeed/ops/lamb/fused_lamb.py:12``).  The reference computes per-tensor
weight/update norms in kernel phases 1-2 and applies the trust-ratio-scaled
update in phase 3.  Here per-tensor norms exploit the flat layout's row
alignment (every tensor owns whole rows): one lane-axis reduction plus a
static slice+sum per tensor (``segment_l2_norms_rows`` — no scatter; the
earlier element-level scatter-add ran 40x slower on TPU), and the update is
one fused elementwise computation with a row-level trust-ratio gather —
same math, a single HBM sweep.

Under ZeRO the segment norms must span shards; the engine computes them
under ``jit`` over the global (logically unsharded) buffer so GSPMD inserts
the cross-shard reduction automatically.
"""

from typing import NamedTuple

import jax.numpy as jnp

from ..op_common import segment_l2_norms_rows


class LambState(NamedTuple):
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray
    step: jnp.ndarray


class FusedLamb:
    """Flat-space LAMB with per-tensor trust ratios.

    Arg names mirror the reference wrapper (``ops/lamb/fused_lamb.py:12-60``):
    ``max_coeff``/``min_coeff`` clamp the trust ratio (lamb coefficient).
    """

    name = "lamb"

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
                 eps_inside_sqrt=False, weight_decay=0.0, max_grad_norm=0.0,
                 max_coeff=10.0, min_coeff=0.01, amsgrad=False, **_ignored):
        if amsgrad:
            raise RuntimeError("FusedLamb does not support the AMSGrad variant.")
        self.bias_correction = bias_correction
        self.eps = eps
        self.eps_inside_sqrt = eps_inside_sqrt
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.param_groups = [{
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
            "max_coeff": max_coeff,
            "min_coeff": min_coeff,
        }]
        self.defaults = {"lr": lr, "betas": tuple(betas)}
        self.lamb_coeffs = []

    def init_state(self, flat_master) -> LambState:
        z = jnp.zeros_like(flat_master)
        return LambState(exp_avg=z, exp_avg_sq=z, step=jnp.asarray(0, jnp.int32))

    def hyperparams(self):
        g = self.param_groups[0]
        return {
            "lr": jnp.asarray(g["lr"], jnp.float32),
            "beta1": jnp.asarray(g["betas"][0], jnp.float32),
            "beta2": jnp.asarray(g["betas"][1], jnp.float32),
            "weight_decay": jnp.asarray(g["weight_decay"], jnp.float32),
        }

    def update(self, state: LambState, flat_master, flat_grads, hp, segments=None,
               segment_ids=None):
        # segment_ids (the element-level device buffer) is unused: the
        # static row layout in `segments` carries everything needed
        assert segments is not None, (
            "FusedLamb needs the segment descriptor for per-tensor trust ratios")
        lr, beta1, beta2, wd = hp["lr"], hp["beta1"], hp["beta2"], hp["weight_decay"]
        g = jnp.asarray(flat_grads, jnp.float32)
        p = flat_master
        step = state.step + 1

        m = beta1 * state.exp_avg + (1.0 - beta1) * g
        v = beta2 * state.exp_avg_sq + (1.0 - beta2) * (g * g)

        if self.bias_correction:
            tf = step.astype(jnp.float32)
            m_hat = m / (1.0 - beta1 ** tf)
            v_hat = v / (1.0 - beta2 ** tf)
        else:
            m_hat, v_hat = m, v

        if self.eps_inside_sqrt:
            denom = jnp.sqrt(v_hat + self.eps)
        else:
            denom = jnp.sqrt(v_hat) + self.eps
        update = m_hat / denom + wd * p

        num_seg = segments.num_segments
        # row-aligned fast path: the element-level scatter version ran a
        # GPT-2-medium LAMB step 40x slower on TPU (huge scatters serialize)
        w_norms = segment_l2_norms_rows(p, segments)
        u_norms = segment_l2_norms_rows(update, segments)
        # trust ratio per tensor: ||w||/||u||, clamped; 1.0 where degenerate
        # (reference kernel phase 3, fused_lamb_cuda_kernel.cu:252-310).
        ratio = jnp.where((w_norms > 0) & (u_norms > 0),
                          jnp.clip(w_norms / u_norms, self.min_coeff, self.max_coeff),
                          jnp.ones_like(w_norms))
        # Padding tail (row segment id == num_seg) gets ratio 1.
        ratio_full = jnp.concatenate([ratio, jnp.ones((1,), jnp.float32)])
        # Row-level gather, broadcast over lanes: an element-level
        # ratio_full[segment_ids] gather sweeps the whole flat buffer
        # through a variable-index gather (measured 2.6 samples/s on
        # GPT-2-medium vs 30+ this way).  Rows are segment-pure, and
        # intra-row padding has update == 0, so its (wrong) per-tensor
        # ratio multiplies zero.
        scale = ratio_full[jnp.asarray(segments.row_segment_ids())][:, None]

        new_p = p - lr * scale * update
        return new_p, LambState(exp_avg=m, exp_avg_sq=v, step=step)

    def get_lamb_coeffs(self):
        return self.lamb_coeffs
