"""Pallas flash attention (forward + backward) for TPU.

TPU-native replacement for the reference's fused attention kernel chain
(QKV strided-batch GEMMs + fused scale/mask softmax + dropout,
``csrc/transformer/ds_transformer_cuda.cpp:145-288``,
``softmax_kernels.cu``).  Instead of materializing the [s, s] score matrix
in HBM, attention is computed blockwise in VMEM with an online softmax
(flash-attention recurrence), so memory is O(s·d) and HBM traffic is one
pass over Q/K/V — this is what buys the "10x longer sequences" capability
the reference got from block-sparse attention (SURVEY §5.7), but for the
dense case.

Layout: inputs are [batch, seq, heads, head_dim]; kernels run on
[batch·heads, seq, head_dim] with a grid over (bh, seq blocks).  All
matmuls hit the MXU with fp32 accumulation (``preferred_element_type``).

The backward pass is the standard flash recurrence: recompute P blockwise
from the saved logsumexp, then
``dv += Pᵀ·dO``, ``ds = P∘(dO·Vᵀ − Δ)``, ``dk += dsᵀ·Q``, ``dq += ds·K``
with ``Δ = rowsum(dO ∘ O)``.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# Running-max floor: keeps exp(NEG_INF - m) == 0 even for rows where every
# key is masked out (m would otherwise be NEG_INF and exp(0) = 1).
MAX_FLOOR = -1e20


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_k, masked):
    if masked:
        kvm_ref, o_ref, lse_ref = rest
    else:
        kvm_ref = None
        o_ref, lse_ref = rest
    qb = q_ref.shape[1]
    d = q_ref.shape[2]
    kv_len = k_ref.shape[1]
    j = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale  # [Bq, d]

    num_kb = pl.cdiv(kv_len, block_k)
    if causal:
        # last k block whose start is <= this q block's end
        num_kb = jax.lax.min(num_kb, pl.cdiv((j + 1) * qb, block_k))

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [Bq, Bk]
        if causal:
            q_idx = j * qb + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        if masked:
            kvm = kvm_ref[0, 0, pl.ds(kb * block_k, block_k)]  # [Bk] fp32 0/1
            s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=1, keepdims=True)),
                            MAX_FLOOR)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((qb, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((qb, 1), jnp.float32)
    acc0 = jnp.zeros((qb, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, block_k, masked):
    if masked:
        kvm_ref, dq_ref = rest
    else:
        kvm_ref = None
        (dq_ref,) = rest
    qb = q_ref.shape[1]
    d = q_ref.shape[2]
    kv_len = k_ref.shape[1]
    j = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]

    num_kb = pl.cdiv(kv_len, block_k)
    if causal:
        num_kb = jax.lax.min(num_kb, pl.cdiv((j + 1) * qb, block_k))

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_idx = j * qb + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        if masked:
            kvm = kvm_ref[0, 0, pl.ds(kb * block_k, block_k)]
            s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((qb, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, block_q, masked):
    if masked:
        kvm_ref, dk_ref, dv_ref = rest
    else:
        kvm_ref = None
        dk_ref, dv_ref = rest
    kb_size = k_ref.shape[1]
    d = k_ref.shape[2]
    q_len = q_ref.shape[1]
    kb = pl.program_id(1)

    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)

    num_qb = pl.cdiv(q_len, block_q)
    if causal:
        first_qb = (kb * kb_size) // block_q
    else:
        first_qb = 0

    def body(qb_i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb_i * block_q, block_q), :].astype(jnp.float32) * scale
        do_blk = do_ref[0, pl.ds(qb_i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qb_i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb_i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_idx = qb_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, kb_size), 0)
            k_idx = kb * kb_size + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, kb_size), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        if masked:
            kvm = kvm_ref[0, 0]  # [Bk] fp32 0/1, this kernel's whole k block
            s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        dv_new = dv + jax.lax.dot_general(p, do_blk, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jax.lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((kb_size, d), jnp.float32)
    dv0 = jnp.zeros((kb_size, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_qb, num_qb, body, (dk0, dv0))
    # q_blk was pre-scaled, so dsᵀ·q_blk already carries the 1/√d factor.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flatten_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflatten_heads(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_attention(q, k, v, kv_mask=None, causal=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """Flash attention on [b, s, h, d]; returns [b, s, h, d].

    ``kv_mask`` is an optional key-padding mask [b, kv_len] with 1 at
    visible keys and 0 at padding (BERT's ``attention_mask`` contract —
    the reference fuses this into its softmax kernel,
    ``csrc/transformer/softmax_kernels.cu``).  Rows with every key masked
    produce zero output and zero gradients.
    """
    out, _ = _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, interpret)
    return out


def _mask_spec(h, kv_len):
    # one [1, 1, kv_len] mask row per (batch·head) program: batch = i // h.
    # The singleton middle axis keeps the block's trailing-two dims at
    # (1, kv_len) == the array dims, which Mosaic's tiling rules accept.
    return pl.BlockSpec((1, 1, kv_len), lambda i, j: (i // h, 0, 0))


def _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    # The kernels index K/V in whole blocks; a ragged tail would silently
    # attend over out-of-block garbage.  Dispatchers (attention.py) only
    # route divisible shapes here; direct callers must pad or shrink blocks.
    if s % block_q != 0 or kv_len % block_k != 0:
        raise ValueError(
            f"flash_attention requires seq divisible by block sizes: "
            f"q_len={s} % block_q={block_q}, kv_len={kv_len} % block_k={block_k}")
    masked = kv_mask is not None
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    bh = b * h
    n_qb = pl.cdiv(s, block_q)

    mask_ops, mask_specs = (), ()
    if masked:
        assert kv_mask.shape == (b, kv_len), (
            f"kv_mask must be [batch, kv_len]={b, kv_len}, got {kv_mask.shape}")
        mask_ops = (kv_mask.astype(jnp.float32)[:, None, :],)
        mask_specs = (_mask_spec(h, kv_len),)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, masked=masked)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            *mask_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, *mask_ops)
    outh = _unflatten_heads(out, b, h)
    return outh, (q, k, v, kv_mask, outh, lse)


def _flash_fwd_rule(q, k, v, kv_mask, causal, block_q, block_k, interpret):
    out, res = _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k, interpret)
    return out, res


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, kv_mask, out, lse = res
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    masked = kv_mask is not None
    scale = 1.0 / math.sqrt(d)
    bh = b * h

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    dof = _flatten_heads(g)
    of = _flatten_heads(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                    keepdims=True).transpose(0, 2, 1)  # [bh, 1, s]

    n_qb = pl.cdiv(s, block_q)
    n_kb = pl.cdiv(kv_len, block_k)

    mask_ops, mask_specs = (), ()
    if masked:
        mask_ops = (kv_mask.astype(jnp.float32)[:, None, :],)
        mask_specs = (_mask_spec(h, kv_len),)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, masked=masked),
        grid=(bh, n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            *mask_specs,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta, *mask_ops)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, masked=masked),
        grid=(bh, n_kb),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0)),
            *((pl.BlockSpec((1, 1, block_k), lambda i, j: (i // h, 0, j)),)
              if masked else ()),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_len, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta, *mask_ops)

    dqh = (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
           _unflatten_heads(dv, b, h))
    return dqh + ((jnp.zeros_like(kv_mask),) if masked else (None,))


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
