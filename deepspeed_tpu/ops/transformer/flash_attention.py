"""Pallas flash attention (forward + backward) for TPU.

TPU-native replacement for the reference's fused attention kernel chain
(QKV strided-batch GEMMs + fused scale/mask softmax + dropout,
``csrc/transformer/ds_transformer_cuda.cpp:145-288``,
``softmax_kernels.cu``).  Instead of materializing the [s, s] score matrix
in HBM, attention is computed blockwise in VMEM with an online softmax
(flash-attention recurrence), so memory is O(s·d) and HBM traffic is one
pass over Q/K/V — this is what buys the "10x longer sequences" capability
the reference got from block-sparse attention (SURVEY §5.7), but for the
dense case.

Layout: inputs are [batch, seq, heads, head_dim]; kernels run on
[batch·heads, seq, head_dim] with a grid over (bh, seq blocks).  All
matmuls hit the MXU with fp32 accumulation (``preferred_element_type``).

The backward pass is the standard flash recurrence: recompute P blockwise
from the saved logsumexp, then
``dv += Pᵀ·dO``, ``ds = P∘(dO·Vᵀ − Δ)``, ``dk += dsᵀ·Q``, ``dq += ds·K``
with ``Δ = rowsum(dO ∘ O)``.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30
# Running-max floor: keeps exp(NEG_INF - m) == 0 even for rows where every
# key is masked out (m would otherwise be NEG_INF and exp(0) = 1).
MAX_FLOOR = -1e20


def _dropout_thresh(rate):
    """Static uint32 threshold + inverse-keep scale for in-kernel dropout.

    Probabilities come from a 32-bit hardware PRNG draw per score entry:
    drop iff ``bits < thresh``.  Quantization error is < 2^-32, so the
    returned scale is unbiased for all practical purposes.
    """
    thresh = int(round(float(rate) * float(1 << 32)))
    thresh = min((1 << 32) - 1, max(1, thresh))
    keep_prob = 1.0 - thresh / float(1 << 32)
    return thresh, 1.0 / keep_prob


def _keep_mask(seed_ref, i, j, kb, shape, thresh):
    """Regenerable [Bq, Bk] keep mask for score tile (i, j, kb).

    Seeding the hardware PRNG with (seed, program ids) makes the draw a pure
    function of the tile coordinates, so the backward kernels regenerate the
    exact forward mask instead of storing an O(s²) byte tensor — same trick
    as the reference's saved-seed cuRAND dropout
    (``csrc/transformer/dropout_kernels.cu``), minus the saved mask.
    """
    # Mosaic takes at most two seed words: mix the tile coordinates into one
    # (wraparound multiplicative hash — deterministic, and identical across
    # the fwd/dq/dkv kernels, which is all that matters).
    tile = (jnp.int32(i) * jnp.int32(1000003)
            + jnp.int32(j)) * jnp.int32(1000003) + jnp.int32(kb)
    pltpu.prng_seed(seed_ref[0], tile)
    bits = jax.lax.bitcast_convert_type(
        pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= jnp.uint32(thresh)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_k, masked,
                dropout):
    rest = list(rest)
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    o_ref, lse_ref = rest
    qb = q_ref.shape[1]
    d = q_ref.shape[2]
    kv_len = k_ref.shape[1]
    j = pl.program_id(1)

    # Matmul inputs stay in the storage dtype (bf16): the MXU natively
    # multiplies bf16 with fp32 accumulation at full rate, while fp32
    # operands run several times slower.  Softmax state (m, l, acc) is fp32.
    q = q_ref[0]  # [Bq, d]

    num_kb = pl.cdiv(kv_len, block_k)
    if causal:
        # last k block whose start is <= this q block's end
        num_kb = jax.lax.min(num_kb, pl.cdiv((j + 1) * qb, block_k))

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [Bq, Bk]
        s = s * scale
        if causal:
            q_idx = j * qb + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        if masked:
            kvm = kvm_ref[0, 0, pl.ds(kb * block_k, block_k)]  # [Bk] fp32 0/1
            s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=1, keepdims=True)),
                            MAX_FLOOR)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        # l accumulates the UNdropped sum (softmax normalizer); dropout hits
        # only the value accumulation, so out == dropout(softmax(s)) @ v.
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            keep = _keep_mask(seed_ref, pl.program_id(0), j, kb,
                              (qb, block_k), thresh)
            p = jnp.where(keep, p * inv_keep, 0.0)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((qb, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((qb, 1), jnp.float32)
    acc0 = jnp.zeros((qb, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, block_k, masked, dropout):
    rest = list(rest)
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    (dq_ref,) = rest
    qb = q_ref.shape[1]
    d = q_ref.shape[2]
    kv_len = k_ref.shape[1]
    j = pl.program_id(1)

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]

    num_kb = pl.cdiv(kv_len, block_k)
    if causal:
        num_kb = jax.lax.min(num_kb, pl.cdiv((j + 1) * qb, block_k))

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = j * qb + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (qb, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        if masked:
            kvm = kvm_ref[0, 0, pl.ds(kb * block_k, block_k)]
            s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            keep = _keep_mask(seed_ref, pl.program_id(0), j, kb,
                              (qb, block_k), thresh)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((qb, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, block_q, masked, dropout):
    rest = list(rest)
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    dk_ref, dv_ref = rest
    kb_size = k_ref.shape[1]
    d = k_ref.shape[2]
    q_len = q_ref.shape[1]
    kb = pl.program_id(1)

    k_blk = k_ref[0]
    v_blk = v_ref[0]

    num_qb = pl.cdiv(q_len, block_q)
    if causal:
        first_qb = (kb * kb_size) // block_q
    else:
        first_qb = 0

    def body(qb_i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb_i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qb_i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb_i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb_i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qb_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, kb_size), 0)
            k_idx = kb * kb_size + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, kb_size), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        if masked:
            kvm = kvm_ref[0, 0]  # [Bk] fp32 0/1, this kernel's whole k block
            s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk] fp32
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            # fwd tile (j=qb_i, kb=program_id(1)) — same seed, same mask
            keep = _keep_mask(seed_ref, pl.program_id(0), qb_i,
                              pl.program_id(1), (block_q, kb_size), thresh)
            p_v = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        else:
            p_v = p
        dv_new = dv + jax.lax.dot_general(p_v.astype(do_blk.dtype), do_blk,
                                          (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q_blk.dtype)
        dk_new = dk + jax.lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((kb_size, d), jnp.float32)
    dv0 = jnp.zeros((kb_size, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_qb, num_qb, body, (dk0, dv0))
    # s was scaled after the q·kᵀ dot, so the 1/√d factor lands on dk here.
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flatten_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflatten_heads(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _auto_blocks(s, kv_len):
    """Largest MXU-friendly blocks the sequence lengths divide into.

    Measured on v5e (B·S = 8k tokens, h16 d64): (256, 512) wins at s=512
    (5.7 ms vs XLA's 6.8), (512, 1024) at s=2048 (8.7 vs 15.8) — the 128²
    blocks this kernel started with leave ~2x on the table (pipeline
    bubbles + sub-MXU dots).
    """
    def pick(n, candidates):
        for c in candidates:
            if n % c == 0:
                return c
        return n

    block_q = pick(s, (512, 256, 128) if s >= 2048 else (256, 128))
    block_k = pick(kv_len, (1024, 512, 256, 128))
    return min(block_q, s), min(block_k, kv_len)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention(q, k, v, kv_mask=None, dropout_seed=None, causal=False,
                    block_q=None, block_k=None,
                    interpret=False, dropout_rate=0.0):
    """Flash attention on [b, s, h, d]; returns [b, s, h, d].

    ``kv_mask`` is an optional key-padding mask [b, kv_len] with 1 at
    visible keys and 0 at padding (BERT's ``attention_mask`` contract —
    the reference fuses this into its softmax kernel,
    ``csrc/transformer/softmax_kernels.cu``).  Rows with every key masked
    produce zero output and zero gradients.

    ``dropout_rate`` > 0 applies attention-probability dropout *inside* the
    kernel: keep masks come from the TPU hardware PRNG seeded by
    (``dropout_seed``, tile coordinates) and are regenerated bit-identically
    in the backward kernels (nothing O(s²) is ever stored — the reference's
    fused softmax-dropout capability, ``dropout_kernels.cu``).
    ``dropout_seed`` is a scalar int32 array; vary it per step/layer.
    TPU-only: requires the Mosaic PRNG (not available in interpret mode).
    """
    out, _ = _flash_fwd(q, k, v, kv_mask, dropout_seed, causal, block_q,
                        block_k, interpret, dropout_rate)
    return out


def _mask_spec(h, kv_len):
    # one [1, 1, kv_len] mask row per (batch·head) program: batch = i // h.
    # The singleton middle axis keeps the block's trailing-two dims at
    # (1, kv_len) == the array dims, which Mosaic's tiling rules accept.
    return pl.BlockSpec((1, 1, kv_len), lambda i, j: (i // h, 0, 0))


def _dropout_ops(dropout_rate, dropout_seed):
    """(operands, specs, active_rate) for the in-kernel dropout seed."""
    if not dropout_rate:
        return (), (), 0.0
    assert dropout_seed is not None, (
        "flash_attention dropout_rate > 0 requires a dropout_seed")
    assert pltpu is not None, "in-kernel dropout needs the pallas TPU backend"
    seed = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))
    return ((seed,), (pl.BlockSpec(memory_space=pltpu.SMEM),),
            float(dropout_rate))


def _flash_fwd(q, k, v, kv_mask, dropout_seed, causal, block_q, block_k,
               interpret, dropout_rate):
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    auto_q, auto_k = _auto_blocks(s, kv_len)
    block_q = block_q or auto_q
    block_k = block_k or auto_k
    # The kernels index K/V in whole blocks; a ragged tail would silently
    # attend over out-of-block garbage.  Dispatchers (attention.py) only
    # route divisible shapes here; direct callers must pad or shrink blocks.
    if s % block_q != 0 or kv_len % block_k != 0:
        raise ValueError(
            f"flash_attention requires seq divisible by block sizes: "
            f"q_len={s} % block_q={block_q}, kv_len={kv_len} % block_k={block_k}")
    masked = kv_mask is not None
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    bh = b * h
    n_qb = pl.cdiv(s, block_q)

    seed_ops, seed_specs, drop = _dropout_ops(dropout_rate, dropout_seed)
    mask_ops, mask_specs = (), ()
    if masked:
        assert kv_mask.shape == (b, kv_len), (
            f"kv_mask must be [batch, kv_len]={b, kv_len}, got {kv_mask.shape}")
        mask_ops = (kv_mask.astype(jnp.float32)[:, None, :],)
        mask_specs = (_mask_spec(h, kv_len),)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, masked=masked, dropout=drop)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            *seed_specs,
            *mask_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, *seed_ops, *mask_ops)
    outh = _unflatten_heads(out, b, h)
    return outh, (q, k, v, kv_mask, dropout_seed, outh, lse)


def _flash_fwd_rule(q, k, v, kv_mask, dropout_seed, causal, block_q, block_k,
                    interpret, dropout_rate):
    out, res = _flash_fwd(q, k, v, kv_mask, dropout_seed, causal, block_q,
                          block_k, interpret, dropout_rate)
    return out, res


def _flash_bwd_rule(causal, block_q, block_k, interpret, dropout_rate, res, g):
    q, k, v, kv_mask, dropout_seed, out, lse = res
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    auto_q, auto_k = _auto_blocks(s, kv_len)
    block_q = block_q or auto_q
    block_k = block_k or auto_k
    masked = kv_mask is not None
    scale = 1.0 / math.sqrt(d)
    bh = b * h

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    dof = _flatten_heads(g)
    of = _flatten_heads(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                    keepdims=True).transpose(0, 2, 1)  # [bh, 1, s]

    n_qb = pl.cdiv(s, block_q)
    n_kb = pl.cdiv(kv_len, block_k)

    seed_ops, seed_specs, drop = _dropout_ops(dropout_rate, dropout_seed)
    mask_ops, mask_specs = (), ()
    if masked:
        mask_ops = (kv_mask.astype(jnp.float32)[:, None, :],)
        mask_specs = (_mask_spec(h, kv_len),)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, masked=masked, dropout=drop),
        grid=(bh, n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, kv_len, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            *seed_specs,
            *mask_specs,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta, *seed_ops, *mask_ops)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, masked=masked, dropout=drop),
        grid=(bh, n_kb),
        in_specs=[
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0)),
            *seed_specs,
            *((pl.BlockSpec((1, 1, block_k), lambda i, j: (i // h, 0, j)),)
              if masked else ()),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_len, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta, *seed_ops, *mask_ops)

    dqh = (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
           _unflatten_heads(dv, b, h))
    return dqh + (jnp.zeros_like(kv_mask) if masked else None, None)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
