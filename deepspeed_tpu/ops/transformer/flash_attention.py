"""Pallas flash attention (forward + backward) for TPU.

TPU-native replacement for the reference's fused attention kernel chain
(QKV strided-batch GEMMs + fused scale/mask softmax + dropout,
``csrc/transformer/ds_transformer_cuda.cpp:145-288``,
``softmax_kernels.cu``).  Instead of materializing the [s, s] score matrix
in HBM, attention is computed blockwise in VMEM with an online softmax
(flash-attention recurrence), so memory is O(s·d) and HBM traffic is one
pass over Q/K/V — this is what buys the "10x longer sequences" capability
the reference got from block-sparse attention (SURVEY §5.7), but for the
dense case.

Layout: inputs are [batch, seq, heads, head_dim]; kernels run on
[batch·heads, seq, head_dim] with a 3-D grid (bh, outer blocks, inner
blocks).  K/V stream through VMEM one block per grid step — VMEM usage is
O(block), not O(seq), so sequence length is bounded by HBM alone — measured
on one v5e chip: BERT-large trains at seq 8192 (1.1 samples/s), 16384, and
32768 (batch 1, per-layer remat), vs the reference's 16x-over-512 best with
block-sparse attention.  Matmul operands stay in the storage
dtype (bf16 runs the MXU at full rate; fp32 operands are several times
slower) with fp32 accumulation; softmax state is fp32 in VMEM scratch.

The backward pass is the standard flash recurrence: recompute P blockwise
from the saved logsumexp, then
``dv += Pᵀ·dO``, ``ds = P∘(dO·Vᵀ − Δ)``, ``dk += dsᵀ·Q``, ``dq += ds·K``
with ``Δ = rowsum(dO ∘ O)``.

In-kernel dropout: keep masks are drawn from the TPU hardware PRNG seeded
by (user seed, tile coordinates), so the backward kernels regenerate the
forward masks bit-for-bit instead of storing an O(s²) mask tensor — the
reference's saved-seed cuRAND trick (``dropout_kernels.cu``) minus the
saved mask.
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is unavailable on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    # Without the pallas TPU package no scratch allocation works (even in
    # interpret mode), so interpret calls fall back to the pure-jnp path
    # (_jnp_flash_reference) and compiled calls raise in _flash_fwd.
    pltpu = None
    _VMEM = None

NEG_INF = -1e30
# Running-max floor: keeps exp(NEG_INF - m) == 0 even for rows where every
# key is masked out (m would otherwise be NEG_INF and exp(0) = 1).
MAX_FLOOR = -1e20

# Base-2 softmax: fold log2(e) into the score scale so the per-element
# transcendental is exp2 instead of exp (one fewer VPU multiply per score
# entry; the probabilities are bit-comparable — exp2(x·log2e) == exp(x) up
# to fp rounding).  The logsumexp residual is stored in base 2 so forward
# and backward agree; the natural-scale 1/√d still lands on dq/dk.  Read
# once at import: the choice bakes into the jit cache (same contract as
# DS_FLASH_ATTENTION — see ADVICE round 3).
EXP2 = os.environ.get("DS_FLASH_EXP2", "0") != "0"
LOG2E = 1.4426950408889634


def _ex(x, exp2):
    return jnp.exp2(x) if exp2 else jnp.exp(x)


def _auto_blocks(s, kv_len, d=64, causal=False):
    """Largest MXU-friendly blocks the sequence lengths divide into.

    Measured on v5e (B·S = 8k tokens, h16 d64): (512, 512) wins at s=512
    (5.4 ms fwd+bwd vs XLA's ~6.8), (512, 2048) at s=2048 (7.3 vs 15.8) —
    128² blocks leave ~2x on the table (pipeline bubbles + sub-MXU dots).
    Bigger k blocks win until the double-buffered K/V block footprint
    presses on scoped VMEM, so block_k·d caps at 128K elements.

    CAUSAL caps block_k at block_q: the skip of above-diagonal work is
    block-granular, so a k block wider than the q block straddles the
    diagonal and executes mostly-masked tiles — kernel-level A/B at
    GPT-2 shape (seq 1024): q512/k1024 10.2 ms fwd+bwd vs q512/k512 7.3.
    End-to-end GPT-2-medium seq-1024 throughput is within noise (attention
    is ~7% of that step); the win grows with seq (more straddling tiles
    avoided) and is free either way.

    Round-4 re-audit (repeated two-point scans, b8 s1024 h16 d64 causal —
    the GPT-2 bench shape, where the step profile puts attention at a
    third of the step): among streamed geometries q512/k512 is stable-best
    at ~3.0 ms fwd+bwd; q256/k512 reads 3.6 ms and q256/k1024 is bistable
    (1.7–3.8 across identical recompiles).  But the SINGLE-TILE path at
    q1024/k1024 beats them all — 2.3–2.6 ms no-dropout, 2.8–3.3 with
    dropout, vs 3.0–3.3 / 3.3–4.3 for the round-3 auto choice — despite
    executing the full (unskipped) score tile: the straight-line softmax
    with no scratch round-trips and full-width PV lanes more than pays for
    the 2x causal MXU waste at this size.  So for causal shapes up to
    s=1024 the auto policy now prefers one full tile; past that the
    streamed q512 geometry still wins (the waste grows quadratically).
    (Also measured, negative: base-2 softmax (DS_FLASH_EXP2) is a wash —
    Mosaic's exp already costs the same as exp2 — and a masked/unmasked
    tile split gains zero; both knobs documented, not defaulted.)
    """
    def pick(n, candidates):
        for c in candidates:
            if n % c == 0:
                return c
        return n

    qcands = (512, 256, 128)
    if (causal and s == kv_len and s <= 1024
            and (128 * 1024) // max(d, 1) >= s):
        # single full tile (see docstring: measured best at the GPT-2
        # shape; n_kb == 1 takes the scratch-free straight-line kernel).
        # The d-gate keeps this to shapes where block_k can also reach s —
        # otherwise the pick would silently swap the measured q512 streamed
        # geometry for an unmeasured q1024 streamed one.
        qcands = (1024,) + qcands
    block_q = pick(s, qcands)
    kmax = max(128, (128 * 1024) // max(d, 1))
    if causal:
        kmax = min(kmax, block_q)
    block_k = pick(kv_len, tuple(
        c for c in (2048, 1024, 512, 256, 128) if c <= kmax))
    return min(block_q, s), min(block_k, kv_len)


def _dropout_thresh(rate):
    """Static uint32 threshold + inverse-keep scale for in-kernel dropout.

    Probabilities come from a 32-bit hardware PRNG draw per score entry:
    drop iff ``bits < thresh``.  Quantization error is < 2^-32, so the
    returned scale is unbiased for all practical purposes.
    """
    # dslint: disable=DSH102 -- rate is a static kernel parameter (functools.partial-bound), never a tracer
    thresh = int(round(float(rate) * float(1 << 32)))
    thresh = min((1 << 32) - 1, max(1, thresh))
    keep_prob = 1.0 - thresh / float(1 << 32)
    return thresh, 1.0 / keep_prob


def _keep_mask(seed_ref, i, j, kb, shape, thresh):
    """Regenerable [Bq, Bk] keep mask for score tile (i, j, kb).

    Seeding the hardware PRNG with (seed words, tile coordinates) makes the
    draw a pure function of the tile, so the backward kernels regenerate the
    exact forward mask.  This Mosaic toolchain accepts AT MOST two seed
    words (a third reliably crashes its compiler — measured), so the 64-bit
    user seed (two int32 words; a single 32-bit per-step seed would
    birthday-collide after ~65k steps) XOR-folds with the coordinates:
    ``bh`` into word 0 and ``(j, kb)`` packed EXACTLY into word 1
    (``j*2^15 + kb`` — both block counts stay far below 2^15 for every
    supported shape), so distinct tiles cannot alias the way a wraparound
    multiplicative hash could.
    """
    tile = jnp.int32(j) * jnp.int32(1 << 15) + jnp.int32(kb)
    pltpu.prng_seed(seed_ref[0] ^ jnp.int32(i), seed_ref[1] ^ tile)
    bits = jax.lax.bitcast_convert_type(
        pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= jnp.uint32(thresh)


def _scores(q_blk, k_blk, scale, causal, masked, kvm_ref, j, kb, block_q,
            block_k):
    """Scaled [Bq, Bk] score tile + causal/key-padding masking."""
    s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_idx = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_idx >= k_idx, s, NEG_INF)
    if masked:
        kvm = kvm_ref[0, 0]  # [Bk] fp32 0/1 — this grid step's k block
        s = jnp.where(kvm[None, :] > 0.0, s, NEG_INF)
    return s


def _fwd_kernel(*refs, scale, causal, masked, dropout, single, exp2):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    rest = refs[3:]
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    o_ref, lse_ref, m_sc, l_sc, acc_sc = rest

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    i, j, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_kb = pl.num_programs(2)
    score_scale = scale * LOG2E if exp2 else scale
    log = jnp.log2 if exp2 else jnp.log

    if single:
        # one k block: straight-line softmax, no scratch round-trips (the
        # common short-sequence case; ~25% faster than the streamed form)
        s = _scores(q_ref[0], k_ref[0], score_scale, causal, masked, kvm_ref,
                    j, kb, block_q, block_k)
        m = jnp.maximum(jnp.max(s, axis=1, keepdims=True), MAX_FLOOR)
        p = _ex(s - m, exp2)
        l = jnp.sum(p, axis=1, keepdims=True)
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            keep = _keep_mask(seed_ref, i, j, kb, (block_q, block_k), thresh)
            p = jnp.where(keep, p * inv_keep, 0.0)
        acc = jax.lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m + log(l_safe))[:, 0]
        return

    @pl.when(kb == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # causal: q rows of block j end at (j+1)·Bq − 1; skip k blocks past
    # them.  The skip saves the compute; the K/V block DMA still happens
    # (BlockSpec fetches are unconditional) — acceptable because K/V bytes
    # are a rounding error next to the score matmuls at these block sizes.
    needed = True if not causal else kb * block_k <= (j + 1) * block_q - 1

    # (round-4 negative result: splitting this step into masked/unmasked
    # variants so fully-below-diagonal tiles skip the causal iota/select
    # measured 3.02 vs 3.00 ms at the GPT-2 shape — Mosaic overlaps that
    # VPU work with the dots already; reverted to the single body)
    @pl.when(needed)
    def _step():
        s = _scores(q_ref[0], k_ref[0], score_scale, causal, masked, kvm_ref,
                    j, kb, block_q, block_k)
        m, l = m_sc[...], l_sc[...]
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s, axis=1, keepdims=True)),
                            MAX_FLOOR)
        p = _ex(s - m_new, exp2)
        corr = _ex(m - m_new, exp2)
        # l accumulates the UNdropped sum (softmax normalizer); dropout hits
        # only the value accumulation, so out == dropout(softmax(s)) @ v.
        l_sc[...] = l * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[...] = m_new
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            keep = _keep_mask(seed_ref, i, j, kb, (block_q, block_k), thresh)
            p = jnp.where(keep, p * inv_keep, 0.0)
        acc_sc[...] = acc_sc[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = l_sc[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_sc[...] + log(l_safe))[:, 0]


def _bwd_dq_kernel(*refs, scale, causal, masked, dropout, single, exp2):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    rest = refs[6:]
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    dq_ref, dq_sc = rest

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    i, j, kb = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_kb = pl.num_programs(2)

    score_scale = scale * LOG2E if exp2 else scale

    def tile_dq():
        s = _scores(q_ref[0], k_ref[0], score_scale, causal, masked, kvm_ref,
                    j, kb, block_q, block_k)
        p = _ex(s - lse_ref[0, 0][:, None], exp2)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            keep = _keep_mask(seed_ref, i, j, kb, (block_q, block_k), thresh)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = (p * (dp - delta_ref[0, 0][:, None])).astype(k_ref.dtype)
        return jax.lax.dot_general(ds, k_ref[0], (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    if single:
        dq_ref[0] = (tile_dq() * scale).astype(dq_ref.dtype)
        return

    @pl.when(kb == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    needed = True if not causal else kb * block_k <= (j + 1) * block_q - 1

    @pl.when(needed)
    def _step():
        dq_sc[...] = dq_sc[...] + tile_dq()

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, masked, dropout, single, exp2):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    rest = refs[6:]
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    dk_ref, dv_ref, dk_sc, dv_sc = rest

    block_k, d = k_ref.shape[1], k_ref.shape[2]
    block_q = q_ref.shape[1]
    # grid is (bh, k blocks, q blocks): q streams in the inner dimension
    i, kb, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n_qb = pl.num_programs(2)

    score_scale = scale * LOG2E if exp2 else scale

    def tile_dkdv():
        s = _scores(q_ref[0], k_ref[0], score_scale, causal, masked, kvm_ref,
                    j, kb, block_q, block_k)
        p = _ex(s - lse_ref[0, 0][:, None], exp2)  # [Bq, Bk] fp32
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout:
            thresh, inv_keep = _dropout_thresh(dropout)
            # fwd tile (j, kb) — same seed hash, same mask
            keep = _keep_mask(seed_ref, i, j, kb, (block_q, block_k), thresh)
            p_v = jnp.where(keep, p * inv_keep, 0.0)
            dp_m = jnp.where(keep, dp * inv_keep, 0.0)
        else:
            p_v, dp_m = p, dp
        dv_t = jax.lax.dot_general(
            p_v.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp_m - delta_ref[0, 0][:, None])).astype(q_ref.dtype)
        dk_t = jax.lax.dot_general(ds, q_ref[0], (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        return dk_t, dv_t

    if single:
        dk_t, dv_t = tile_dkdv()
        # s was scaled after the q·kᵀ dot, so the 1/√d factor lands on dk.
        dk_ref[0] = (dk_t * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_t.astype(dv_ref.dtype)
        return

    @pl.when(j == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    # causal: q block j contributes to k block kb iff its last row can see
    # the block's first key
    needed = True if not causal else (j + 1) * block_q - 1 >= kb * block_k

    @pl.when(needed)
    def _step():
        dk_t, dv_t = tile_dkdv()
        dk_sc[...] = dk_sc[...] + dk_t
        dv_sc[...] = dv_sc[...] + dv_t

    @pl.when(j == n_qb - 1)
    def _finalize():
        # s was scaled after the q·kᵀ dot, so the 1/√d factor lands on dk.
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(*refs, scale, causal, masked, dropout, exp2):
    """Single-tile fused backward: dq, dk, dv from ONE score
    materialization.  The streamed pair (_bwd_dq_kernel + _bwd_dkv_kernel)
    each recompute the q·kᵀ scores, the softmax exp, the dᵒ·vᵀ dot and —
    under dropout — the PRNG mask; at the single-tile shapes the auto
    policy picks for s ≤ 1024 (GPT-2 s=1024 causal, BERT s=512) the whole
    tile fits VMEM, so one straight-line kernel computes p and ds once
    and feeds all three gradient dots (round-5 follow-up to the round-4b
    single-tile forward: the same win applied to the backward)."""
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    rest = refs[6:]
    seed_ref = rest.pop(0) if dropout else None
    kvm_ref = rest.pop(0) if masked else None
    dq_ref, dk_ref, dv_ref = rest

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    i = pl.program_id(0)
    score_scale = scale * LOG2E if exp2 else scale

    s = _scores(q_ref[0], k_ref[0], score_scale, causal, masked, kvm_ref,
                0, 0, block_q, block_k)
    p = _ex(s - lse_ref[0, 0][:, None], exp2)  # [Bq, Bk] fp32
    dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if dropout:
        thresh, inv_keep = _dropout_thresh(dropout)
        keep = _keep_mask(seed_ref, i, 0, 0, (block_q, block_k), thresh)
        p_v = jnp.where(keep, p * inv_keep, 0.0)
        dp = jnp.where(keep, dp * inv_keep, 0.0)
    else:
        p_v = p
    ds = (p * (dp - delta_ref[0, 0][:, None])).astype(q_ref.dtype)
    dq_ref[0] = (jax.lax.dot_general(
        ds, k_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale).astype(dq_ref.dtype)
    # s was scaled after the q·kᵀ dot, so the 1/√d factor lands on dk too
    dk_ref[0] = (jax.lax.dot_general(
        ds, q_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale).astype(dk_ref.dtype)
    dv_ref[0] = jax.lax.dot_general(
        p_v.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)


def _flatten_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflatten_heads(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention(q, k, v, kv_mask=None, dropout_seed=None, causal=False,
                    block_q=None, block_k=None,
                    interpret=False, dropout_rate=0.0):
    """Flash attention on [b, s, h, d]; returns [b, s, h, d].

    ``kv_mask`` is an optional key-padding mask [b, kv_len] with 1 at
    visible keys and 0 at padding (BERT's ``attention_mask`` contract —
    the reference fuses this into its softmax kernel,
    ``csrc/transformer/softmax_kernels.cu``).  Rows with every key masked
    produce zero output and zero gradients.

    ``dropout_rate`` > 0 applies attention-probability dropout *inside* the
    kernel: keep masks come from the TPU hardware PRNG seeded by
    (``dropout_seed``, tile coordinates) and are regenerated bit-identically
    in the backward kernels (nothing O(s²) is ever stored — the reference's
    fused softmax-dropout capability, ``dropout_kernels.cu``).
    ``dropout_seed`` is a scalar int32 array; vary it per step/layer.
    TPU-only: requires the Mosaic PRNG (not available in interpret mode).
    """
    out, _ = _flash_fwd(q, k, v, kv_mask, dropout_seed, causal, block_q,
                        block_k, interpret, dropout_rate)
    return out


def _mask_spec(h, block_k):
    # one [1, 1, block_k] mask slice per (batch·head, k block) program:
    # batch = i // h.  The singleton middle axis keeps the block's
    # trailing-two dims Mosaic-tileable.
    return pl.BlockSpec((1, 1, block_k), lambda i, j, kb: (i // h, 0, kb))


def _dropout_ops(dropout_rate, dropout_seed):
    """(operands, specs, active_rate) for the in-kernel dropout seed."""
    if not dropout_rate:
        return (), (), 0.0
    assert dropout_seed is not None, (
        "flash_attention dropout_rate > 0 requires a dropout_seed")
    assert pltpu is not None, "in-kernel dropout needs the pallas TPU backend"
    seed = jnp.asarray(dropout_seed, jnp.int32).reshape(-1)
    if seed.size == 1:  # legacy scalar seed: widen with a zero hi word
        seed = jnp.concatenate([seed, jnp.zeros((1,), jnp.int32)])
    assert seed.size == 2, f"dropout_seed must be 1 or 2 int32 words, got {seed.size}"
    return ((seed,), (pl.BlockSpec(memory_space=pltpu.SMEM),),
            float(dropout_rate))  # dslint: disable=DSH102 -- dropout_rate rides custom_vjp nondiff_argnums: static by construction


def _resolve_blocks(s, kv_len, d, block_q, block_k, causal=False,
                    dropout_rate=0.0):
    auto_q, auto_k = _auto_blocks(s, kv_len, d, causal)
    if block_q is None and block_k is None:
        # runtime autotune (reference analog: the GEMM algorithm search
        # baked into kernel setup, csrc/includes/gemm_test.h): shapes the
        # hand calibration covers keep the measured heuristic choice;
        # anything else gets a cached first-use micro-search.  tune()
        # calls back into flash_attention with EXPLICIT blocks, so the
        # recursion terminates here.
        from .kernel_tuner import tune
        auto_q, auto_k = tune(s, kv_len, d, causal, dropout_rate,
                              flash_attention, (auto_q, auto_k))
    block_q = block_q or auto_q
    block_k = block_k or auto_k
    # The kernels index K/V in whole blocks; a ragged tail would silently
    # attend over out-of-block garbage.  Dispatchers (attention.py) only
    # route divisible shapes here; direct callers must pad or shrink blocks.
    if s % block_q != 0 or kv_len % block_k != 0:
        raise ValueError(
            f"flash_attention requires seq divisible by block sizes: "
            f"q_len={s} % block_q={block_q}, kv_len={kv_len} % block_k={block_k}")
    return block_q, block_k


def _grid_params(interpret):
    if pltpu is None or interpret:
        return {}
    # bh and the outer block dim are parallel; the streamed dim accumulates
    # into VMEM scratch and must run in order.  The raised vmem limit lets
    # XLA keep large kernel outputs in VMEM when it judges that profitable
    # (v5e has 128M; the default 16M scoped limit rejects long-sequence
    # outputs it would otherwise promote).
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=100 * 1024 * 1024)}


def _jnp_flash_reference(q, k, v, kv_mask, causal):
    """Dense jnp forward with the kernels' exact masking semantics —
    the scratch-free interpret-mode path for CPU-only jax builds where
    ``jax.experimental.pallas.tpu`` is unimportable (O(s²) memory, test
    shapes only).  Returns (out [b,s,h,d], lse [b·h, 1, s])."""
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    if causal:
        q_idx = jnp.arange(s)[:, None]
        k_idx = jnp.arange(kv_len)[None, :]
        sc = jnp.where((q_idx >= k_idx)[None, None], sc, NEG_INF)
    if kv_mask is not None:
        sc = jnp.where(kv_mask.astype(jnp.float32)[:, None, None, :] > 0.0,
                       sc, NEG_INF)
    m = jnp.maximum(jnp.max(sc, axis=-1, keepdims=True), MAX_FLOOR)
    p = jnp.exp(sc - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / l_safe).astype(v.dtype), v)
    lse = (m + jnp.log(l_safe))[..., 0].reshape(b * h, 1, s)
    return out.astype(q.dtype), lse


def _flash_fwd(q, k, v, kv_mask, dropout_seed, causal, block_q, block_k,
               interpret, dropout_rate):
    if pltpu is None:
        if not interpret:
            raise RuntimeError(
                "flash_attention needs jax.experimental.pallas.tpu for "
                "compiled kernels, which this jax build could not import — "
                "use attn_impl='auto' on a CPU backend (XLA attention) "
                "instead")
        assert not dropout_rate, (
            "in-kernel dropout needs the pallas TPU backend (hardware PRNG)")
        out, lse = _jnp_flash_reference(q, k, v, kv_mask, causal)
        return out, (q, k, v, kv_mask, dropout_seed, out, lse)
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    block_q, block_k = _resolve_blocks(s, kv_len, d, block_q, block_k, causal,
                                       dropout_rate)
    masked = kv_mask is not None
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    bh = b * h
    n_qb = pl.cdiv(s, block_q)
    n_kb = pl.cdiv(kv_len, block_k)

    seed_ops, seed_specs, drop = _dropout_ops(dropout_rate, dropout_seed)
    mask_ops, mask_specs = (), ()
    if masked:
        assert kv_mask.shape == (b, kv_len), (
            f"kv_mask must be [batch, kv_len]={b, kv_len}, got {kv_mask.shape}")
        mask_ops = (kv_mask.astype(jnp.float32)[:, None, :],)
        mask_specs = (_mask_spec(h, block_k),)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               masked=masked, dropout=drop,
                               single=(n_kb == 1), exp2=EXP2)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
            *seed_specs,
            *mask_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        scratch_shapes=[
            _VMEM((block_q, 1), jnp.float32),   # running max m
            _VMEM((block_q, 1), jnp.float32),   # running sum l
            _VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(qf, kf, vf, *seed_ops, *mask_ops)
    outh = _unflatten_heads(out, b, h)
    return outh, (q, k, v, kv_mask, dropout_seed, outh, lse)


def _flash_fwd_rule(q, k, v, kv_mask, dropout_seed, causal, block_q, block_k,
                    interpret, dropout_rate):
    out, res = _flash_fwd(q, k, v, kv_mask, dropout_seed, causal, block_q,
                          block_k, interpret, dropout_rate)
    return out, res


def _flash_bwd_rule(causal, block_q, block_k, interpret, dropout_rate, res, g):
    q, k, v, kv_mask, dropout_seed, out, lse = res
    if pltpu is None:  # interpret fallback (see _flash_fwd); no dropout
        dq, dk, dv = jax.vjp(
            lambda q_, k_, v_: _jnp_flash_reference(q_, k_, v_, kv_mask,
                                                    causal)[0],
            q, k, v)[1](g)
        return (dq, dk, dv,
                jnp.zeros_like(kv_mask) if kv_mask is not None else None,
                None)
    b, s, h, d = q.shape
    kv_len = k.shape[1]
    block_q, block_k = _resolve_blocks(s, kv_len, d, block_q, block_k, causal,
                                       dropout_rate)
    masked = kv_mask is not None
    scale = 1.0 / math.sqrt(d)
    bh = b * h

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    dof = _flatten_heads(g)
    of = _flatten_heads(out)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                    keepdims=True).transpose(0, 2, 1)  # [bh, 1, s]

    n_qb = pl.cdiv(s, block_q)
    n_kb = pl.cdiv(kv_len, block_k)

    seed_ops, seed_specs, drop = _dropout_ops(dropout_rate, dropout_seed)
    mask_ops, mask_specs = (), ()
    if masked:
        mask_ops = (kv_mask.astype(jnp.float32)[:, None, :],)
        mask_specs = (_mask_spec(h, block_k),)

    if n_qb == 1 and n_kb == 1:
        # single-tile fused backward: one kernel, one score pass
        grid_1d = ({} if (pltpu is None or interpret) else
                   {"compiler_params": pltpu.CompilerParams(
                       dimension_semantics=("parallel",),
                       vmem_limit_bytes=100 * 1024 * 1024)})
        fused_seed_specs = seed_specs
        fused_mask_specs = ((pl.BlockSpec((1, 1, block_k),
                                          lambda i: (i // h, 0, 0)),)
                            if masked else ())
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                              masked=masked, dropout=drop, exp2=EXP2),
            grid=(bh,),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block_k, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block_k, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block_q, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, 1, block_q), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, 1, block_q), lambda i: (i, 0, 0)),
                *fused_seed_specs,
                *fused_mask_specs,
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block_k, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, block_k, d), lambda i: (i, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                jax.ShapeDtypeStruct((bh, kv_len, d), k.dtype),
                jax.ShapeDtypeStruct((bh, kv_len, d), v.dtype),
            ],
            interpret=interpret,
            **grid_1d,
        )(qf, kf, vf, dof, lse, delta, *seed_ops, *mask_ops)
        dqh = (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
               _unflatten_heads(dv, b, h))
        return dqh + (jnp.zeros_like(kv_mask) if masked else None, None)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          masked=masked, dropout=drop, single=(n_kb == 1),
                          exp2=EXP2),
        grid=(bh, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            *seed_specs,
            *mask_specs,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[_VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        **_grid_params(interpret),
    )(qf, kf, vf, dof, lse, delta, *seed_ops, *mask_ops)

    # grid (bh, k blocks, q blocks): mask/seed specs take (i, kb, j) index
    # order, so the kb-indexed mask slice rides program_id(1)
    dkv_mask_specs = ((pl.BlockSpec((1, 1, block_k),
                                    lambda i, kb, j: (i // h, 0, kb)),)
                      if masked else ())
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          masked=masked, dropout=drop, single=(n_qb == 1),
                          exp2=EXP2),
        grid=(bh, n_kb, n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, kb, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, kb, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, kb, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, kb, j: (i, 0, j)),
            *seed_specs,
            *dkv_mask_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_len, d), v.dtype),
        ],
        scratch_shapes=[
            _VMEM((block_k, d), jnp.float32),
            _VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(qf, kf, vf, dof, lse, delta, *seed_ops, *mask_ops)

    dqh = (_unflatten_heads(dq, b, h), _unflatten_heads(dk, b, h),
           _unflatten_heads(dv, b, h))
    return dqh + (jnp.zeros_like(kv_mask) if masked else None, None)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
