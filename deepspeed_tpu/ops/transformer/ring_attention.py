"""Ring attention: exact attention over sequence-sharded Q/K/V.

First-class long-context support (SURVEY §5.7: the 2020 reference's
long-sequence story was block-sparse attention + activation
checkpointing/offload; ring attention is the TPU-era upgrade called for by
the rebuild plan, SURVEY §7 step 7).  The sequence is sharded over the
``seq`` mesh axis; each device keeps its Q shard resident and the K/V
shards rotate around the ring via ``ppermute`` while a streaming
(flash-style) softmax accumulates the exact result:

    m, l, o ← running row-max, normalizer, unnormalized output
    for step t in 0..N-1:
        attend local Q against the currently-held K/V chunk
        rotate K/V to the next ring neighbor          [ICI ppermute]

Compute is O(s²/N) per device with only O(s/N) resident activations, the
per-chunk matmuls stay MXU-shaped, and XLA overlaps the ppermute with the
chunk compute (the collective-permute latency hides behind the attention
matmuls once chunks are big enough).  Backward is autodiff through the
scan: the K/V rotation transposes to the reverse rotation, giving the
standard ring-attention backward without hand-written communication.

Causality is handled per (q-shard, kv-chunk) pair from global positions:
chunks strictly above the diagonal contribute nothing (masked with a
finite -1e9 so gradients stay NaN-free).
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import SEQ_AXIS
from ...utils.compat import shard_map

NEG = -1e9


def _ring_attention_local(q, k, v, kpm, axis_name, nshards, causal, scale):
    """Per-shard body (inside shard_map): q/k/v are local chunks
    [b, s_loc, h, d]; kpm an additive [b, s_loc] key-padding-mask chunk
    (or None) that rotates around the ring with its K/V chunk."""
    me = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    qpos = me * s_loc + jnp.arange(s_loc)  # global query positions
    perm = [(i, (i + 1) % nshards) for i in range(nshards)]

    q32 = q.astype(jnp.float32)
    if kpm is None:
        kpm = jnp.zeros((b, s_loc), jnp.float32)

    def step(carry, t):
        k_cur, v_cur, kpm_cur, m, l, o = carry
        src = (me - t) % nshards  # which chunk we hold this step
        kpos = src * s_loc + jnp.arange(s_loc)

        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_cur.astype(jnp.float32))
        scores = scores * scale
        scores = scores + kpm_cur[:, None, None, :].astype(jnp.float32)
        if causal:
            mask = kpos[None, :] <= qpos[:, None]  # [s_q, s_k]
            scores = jnp.where(mask[None, None], scores, NEG)

        chunk_max = jnp.max(scores, axis=-1)  # [b, h, sq]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32))

        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        kpm_next = jax.lax.ppermute(kpm_cur, axis_name, perm)
        return (k_next, v_next, kpm_next, new_m, l_new, o_new), None

    m0 = jnp.full((b, h, s_loc), NEG, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    (_, _, _, m, l, o), _ = jax.lax.scan(step, (k, v, kpm, m0, l0, o0),
                                         jnp.arange(nshards))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [b, s_loc, h, d]


def ring_attention(q, k, v, mesh=None, axis_name=SEQ_AXIS, causal=False,
                   key_padding_mask=None, scale=None):
    """Exact attention with Q/K/V sequence-sharded over ``axis_name``.

    Args:
        q, k, v: ``[batch, seq, heads, head_dim]`` global arrays whose seq
            dim is (or will be) sharded over ``axis_name``.
        mesh: the device mesh (defaults to the engine-registered current
            mesh).
        causal: autoregressive masking using global positions.
        key_padding_mask: additive ``[batch, seq]`` (-inf at masked keys);
            its chunks rotate around the ring alongside K/V.

    Falls back to a single-device dense computation when the axis has size 1.
    """
    if mesh is None:
        from ...parallel.mesh import get_current_mesh

        mesh = get_current_mesh()
        assert mesh is not None, (
            "ring_attention needs a mesh (pass mesh= or initialize the "
            "engine, which registers the current mesh)")
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    nshards = shape.get(axis_name, 1)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)

    from ...utils.compat import PARTIAL_MANUAL_SHARD_MAP

    if nshards == 1 or not PARTIAL_MANUAL_SHARD_MAP:
        # single seq shard — or old jax, where the partial-manual ring
        # program cannot compile (see utils/compat.py): same math, dense,
        # GSPMD-sharded instead of ring-scheduled
        from .attention import reference_attention

        mask4 = (key_padding_mask[:, None, None, :]
                 if key_padding_mask is not None else None)
        # reference_attention hard-codes 1/sqrt(d); fold any custom scale
        # in by pre-scaling q so both paths compute the same scores
        q_eff = q * (scale * math.sqrt(d)) if scale != 1.0 / math.sqrt(d) \
            else q
        return reference_attention(q_eff, k, v, mask=mask4, causal=causal)

    body = partial(_ring_attention_local, axis_name=axis_name,
                   nshards=nshards, causal=causal, scale=scale)
    spec = P(None, axis_name)  # shard the seq dim (axis 1)
    if key_padding_mask is None:
        fn = shard_map(lambda q, k, v: body(q, k, v, None), mesh=mesh,
                           in_specs=(spec, spec, spec), out_specs=spec,
                           axis_names={axis_name}, check_vma=False)
        return fn(q, k, v)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec, spec),
                       out_specs=spec, axis_names={axis_name},
                       check_vma=False)
    return fn(q, k, v, key_padding_mask)
