"""Runtime block autotuning for the flash-attention kernels.

The reference bakes a GEMM autotuner into kernel setup — every transformer
kernel build runs a small search over algorithms and caches the winner
(``/root/reference/csrc/includes/gemm_test.h``).  This is the TPU analog
for the Pallas flash kernels: the hand-calibrated ``_auto_blocks``
heuristic stays authoritative for the shapes it was measured on (the
"anchored" regimes below — re-tuning those would risk regressing measured
choices on a noisy attachment), and any OTHER shape gets a cached
first-use micro-search over a small block-geometry candidate set.

Search cost is one kernel compile per candidate (~4-6 candidates) the
first time a new (seq, kv_len, head_dim, causal, dropout) shape is seen
on a TPU backend; winners persist to a JSON cache
(``~/.cache/deepspeed_tpu/flash_blocks.json`` or ``$DS_FLASH_TUNE_CACHE``)
so every later process skips straight to the tuned geometry.

Measurement discipline (PERF.md "Methodology"): candidates run under one
``lax.scan`` inside a single jit (per-dispatch latency on remote-attached
chips is ~70-100 ms and identical across candidates, so it cancels in the
ranking), with three interleaved repeats and min-aggregation — single
shots at ms granularity swing +-50% on the bench attachment.

Knobs: ``DS_FLASH_AUTOTUNE=0`` disables the search (pure heuristic),
``=1`` forces tuning even for anchored shapes, unset/``auto`` tunes only
un-anchored shapes on TPU backends.
"""

import json
import logging
import os
import time

import jax
import jax.numpy as jnp

_CACHE_PATH = os.environ.get(
    "DS_FLASH_TUNE_CACHE",
    os.path.expanduser("~/.cache/deepspeed_tpu/flash_blocks.json"))
_memory_cache = {}
_disk_loaded = False

# Tuner algorithm revision, part of every cache key: winners persist to
# disk indefinitely, so a ranking fixed by a later tuner (candidate set,
# timing discipline, screening) must INVALIDATE cached pre-fix winners —
# keying by shape+device alone let mis-ranked geometries outlive the
# tuner that produced them (VERDICT r5).  Bump this when the search
# changes in any way that can alter a winner; stale-version entries are
# simply ignored (and rewritten on the next tune of that shape).
#
# v2: version-carrying keys; retires v1 entries ranked before the
# interleaved-repeat/min-aggregation discipline carried its own version.
TUNER_VERSION = 2


def _mode():
    return os.environ.get("DS_FLASH_AUTOTUNE", "auto")


def anchored(s, kv_len, d, causal):
    """Shapes the hand calibration covers (PERF.md measured anchors):
    d=64 self-attention at power-of-two-ish lengths where _auto_blocks'
    choice was A/B-measured on chip.  Everything else is fair game for
    the runtime search."""
    if d != 64 or kv_len != s:
        return False
    if causal and s <= 1024:
        return True  # single-tile path, measured best (round 4b)
    return s in (128, 256, 512, 1024, 2048, 4096, 8192, 16384, 32768)


def _key(s, kv_len, d, causal, dropout, device_kind=""):
    # device_kind in the key: a geometry tuned on a v5e must not be
    # silently reused on a v4/v5p (different VMEM/MXU/bandwidth).
    # TUNER_VERSION in the key: a geometry ranked by an older tuner
    # must not be silently reused by a newer one.
    dk = device_kind.replace("|", "_").replace(" ", "_")
    return (f"v{TUNER_VERSION}|{dk}|s{s}|kv{kv_len}|d{d}|c{int(causal)}"
            f"|p{int(dropout > 0)}")


def _load_disk():
    global _disk_loaded
    if _disk_loaded:
        return
    _disk_loaded = True
    try:
        with open(_CACHE_PATH) as f:
            _memory_cache.update(json.load(f))
    except Exception:  # dslint: disable=DSE502 -- cache file absent/corrupt on first run; tuner just re-measures
        pass


def _save_disk():
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        with open(_CACHE_PATH, "w") as f:
            json.dump(_memory_cache, f, indent=1, sort_keys=True)
    except Exception:  # dslint: disable=DSE502 -- read-only FS etc.; in-memory cache still works
        pass


def candidates(s, kv_len, d, causal):
    """Small but diverse block-geometry set.  VMEM cap mirrors
    _auto_blocks: block_k * d <= 128K elements."""
    kmax_el = (128 * 1024) // max(d, 1)
    qs = [c for c in (1024, 512, 256, 128) if c <= s and s % c == 0]
    ks = [c for c in (2048, 1024, 512, 256, 128)
          if c <= min(kv_len, kmax_el) and kv_len % c == 0]
    out = []
    for q in qs[:3]:
        for k in ks:
            if causal and k > q:
                continue  # measured: straddling tiles lose (PERF.md)
            out.append((q, k))
    # single-tile candidate where it fits VMEM (the round-4b winner
    # regime, generalized to other d)
    if s == kv_len and s <= kmax_el and s % 128 == 0 and (s, s) not in out:
        out.append((s, s))
    # dedupe preserving order, cap the search
    seen, uniq = set(), []
    for c in out:
        if c not in seen:
            seen.add(c)
            uniq.append(c)
    return uniq[:6]


def tune(s, kv_len, d, causal, dropout, flash_fn, heuristic, bh=8):
    """Search block geometries for one shape; returns (block_q, block_k).

    ``flash_fn(q, k, v, block_q=, block_k=, causal=, dropout_seed=,
    dropout_rate=)`` is the kernel entry (passed in to avoid a circular
    import); ``heuristic`` is the fallback/first candidate."""
    if _mode() == "0":
        return heuristic
    try:
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return heuristic  # search is only meaningful on the target HW
        kind = getattr(dev, "device_kind", "tpu")
    except Exception:
        return heuristic
    key = _key(s, kv_len, d, causal, dropout, kind)
    _load_disk()
    if key in _memory_cache:
        return tuple(_memory_cache[key])
    if _mode() != "1" and anchored(s, kv_len, d, causal):
        return heuristic

    cands = candidates(s, kv_len, d, causal)
    if heuristic not in cands:
        cands.insert(0, heuristic)
    logging.getLogger("DeepSpeedTPU").info(
        "flash-attention autotune: first use of shape s=%d kv=%d d=%d "
        "causal=%s — compiling and timing %d block geometries (one-time; "
        "cached at %s; DS_FLASH_AUTOTUNE=0 disables)",
        s, kv_len, d, causal, len(cands), _CACHE_PATH)

    kq = jax.random.PRNGKey(0)
    q = jax.random.normal(kq, (1, s, bh, d), jnp.bfloat16)
    k = jax.random.normal(kq, (1, kv_len, bh, d), jnp.bfloat16)
    v = jax.random.normal(kq, (1, kv_len, bh, d), jnp.bfloat16)
    seed = jnp.zeros((2,), jnp.int32) if dropout else None

    def make_run(bq, bk):
        def loss(q_, k_, v_):
            out = flash_fn(q_, k_, v_, causal=causal, block_q=bq,
                           block_k=bk, dropout_seed=seed,
                           dropout_rate=dropout)
            return jnp.sum(out.astype(jnp.float32))

        @jax.jit
        def run(q_, k_, v_):
            def body(c, _):
                l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                    q_ + c.astype(jnp.bfloat16), k_, v_)
                # fold the GRADIENTS into the carry too: an unused grads
                # output would be dead-code-eliminated and the candidates
                # ranked (and compile-screened) on the forward alone
                gtok = sum(g.reshape(-1)[0].astype(jnp.float32)
                           for g in grads)
                return c + (l + gtok) * 1e-30, None
            c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=8)
            return c
        return run

    runners = {}
    for bq, bk in cands:
        run = make_run(bq, bk)
        try:
            run(q, k, v).block_until_ready()  # compile + warm
            runners[(bq, bk)] = run
        except Exception:
            continue  # candidate doesn't compile at this shape — skip
    if not runners:
        return heuristic

    # INTERLEAVED repeats with min-aggregation (PERF.md methodology:
    # single shots swing ±50% on remote attachments, and back-to-back
    # repeats let one load spike mis-rank a whole candidate)
    results = {c: [] for c in runners}
    for _ in range(3):
        for c, run in runners.items():
            t0 = time.perf_counter()
            float(jax.device_get(run(q, k, v)))
            results[c].append(time.perf_counter() - t0)

    best = min(results, key=lambda c: min(results[c]))
    _memory_cache[key] = list(best)
    _save_disk()
    return best
