"""Attention dispatch: Pallas flash attention on TPU, jnp reference elsewhere.

This is the TPU-native stand-in for the reference's fused attention kernel
chain (strided-batch GEMMs + fused scale/mask softmax,
``csrc/transformer/softmax_kernels.cu``, ``ds_transformer_cuda.cpp:145``).
The Pallas path (``ops/transformer/flash_attention.py``) computes attention
blockwise without materializing the [s, s] score matrix (flash-attention
style), which is both the memory story (long sequences) and the HBM-
bandwidth story on TPU.
"""

import math
import os

import jax
import jax.numpy as jnp

from ..op_common import random_keep

# Dispatch policy, measured on v5e (BERT-large shapes, h16 d64):
# - short sequences (128-256): XLA's batched attention wins — blocks are too
#   small for the flash pipeline (seq 128: 416 vs 344 samples/s end-to-end);
# - seq >= 512: the tuned-block Pallas kernel wins (seq 512: 5.4 vs 6.8 ms
#   fwd+bwd; seq 2048: 7.3 vs 15.8 ms — see flash_attention._auto_blocks,
#   the authoritative tuning record) AND never materializes the [s, s]
#   score tensor, which is also what lifts the memory ceiling for long
#   sequences.  DS_FLASH_ATTENTION=always|never|auto overrides.
PALLAS_MIN_SEQ = 512
PALLAS_MIN_SCORE_BYTES = 2 * 1024 ** 3


def _use_pallas(q, k):
    try:
        mode = os.environ.get("DS_FLASH_ATTENTION", "auto")
        shapes_ok = (jax.default_backend() == "tpu" and q.shape[1] >= 128
                     and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
                     and q.shape[-1] % 64 == 0)
        if mode == "never":
            return False
        if mode == "always":
            return shapes_ok
        if q.shape[1] >= PALLAS_MIN_SEQ and k.shape[1] >= PALLAS_MIN_SEQ:
            return shapes_ok
        b, sq, h, _ = q.shape
        score_bytes = 4 * b * h * sq * k.shape[1]
        # shapes here are logical/global; under data-parallel GSPMD each
        # chip materializes 1/dp of the batch — budget the PER-DEVICE size
        try:
            from ...parallel.mesh import get_current_mesh

            mesh = get_current_mesh()
            if mesh is not None:
                dp = dict(zip(mesh.axis_names, mesh.devices.shape)).get(
                    "data", 1)
                score_bytes //= max(dp, 1)
        except Exception:  # dslint: disable=DSE502 -- mesh probe inside a heuristic; undivided score is a safe default
            pass
        return shapes_ok and score_bytes > PALLAS_MIN_SCORE_BYTES
    except Exception:
        return False


def key_padding_to_additive(key_padding_mask):
    """[b, s] 1/0 key-padding mask -> additive [b, s] bias (0 / -1e9)."""
    return (1.0 - key_padding_mask.astype(jnp.float32)) * -1e9


def reference_attention(q, k, v, mask=None, causal=False, dropout_rate=0.0,
                        dropout_rng=None, deterministic=True):
    """jnp attention: [b, s, h, d] inputs, fp32 softmax accumulation."""
    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((s, k.shape[1]), bool))
        scores = jnp.where(causal_mask[None, None], scores, jnp.float32(-1e9))
    if mask is not None:
        scores = scores + mask.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    if (not deterministic and dropout_rate >= 1.0 / 512.0
            and dropout_rng is not None):
        # one random byte per element in compute dtype (the reference kernel
        # likewise drops the fp16 softmax output, dropout_kernels.cu); rates
        # below the 1/256 quantum pass through, matching layers.dropout
        keep, inv_keep = random_keep(dropout_rng, probs.shape, dropout_rate)
        probs = jnp.where(keep, probs * jnp.asarray(inv_keep, probs.dtype), 0.0)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return ctx


def dot_product_attention(q, k, v, mask=None, key_padding_mask=None,
                          causal=False, dropout_rate=0.0,
                          dropout_rng=None, deterministic=True):
    """Multi-head attention on [batch, seq, heads, head_dim] tensors.

    ``mask`` is an additive bias broadcastable to [b, h, q, k] (e.g. a
    padding mask of -1e9 at masked keys), matching the reference layer's
    attention-mask contract (``ops/transformer/transformer.py:155-244``).
    ``key_padding_mask`` is the structured special case the flash kernel
    fuses (reference: fused scale+mask softmax,
    ``csrc/transformer/softmax_kernels.cu``): [b, kv_len] with 1 at visible
    keys, 0 at padding.  Pass one or the other, not both.
    """
    assert mask is None or key_padding_mask is None, (
        "pass either an additive mask or a key_padding_mask, not both")
    if _use_pallas(q, k) and mask is None:
        from .flash_attention import flash_attention

        seed, rate = None, 0.0
        if (not deterministic and dropout_rate >= 1.0 / 512.0
                and dropout_rng is not None):
            # in-kernel probs dropout: hand the kernel 64 bits of seed
            # material from this call's rng stream (32 bits would
            # birthday-collide across steps after ~65k draws)
            seed = jax.lax.bitcast_convert_type(
                jax.random.bits(dropout_rng, (2,), jnp.uint32), jnp.int32)
            rate = float(dropout_rate)
        return flash_attention(q, k, v, kv_mask=key_padding_mask,
                               dropout_seed=seed, causal=causal,
                               dropout_rate=rate)
    if key_padding_mask is not None:
        mask = key_padding_to_additive(key_padding_mask)[:, None, None, :]
    return reference_attention(q, k, v, mask=mask, causal=causal,
                               dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                               deterministic=deterministic)
