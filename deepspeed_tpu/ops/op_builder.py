"""Op registry — the TPU analog of the reference's ``op_builder`` package.

The reference (``op_builder/builder.py:78-260``) compiles CUDA extensions
ahead-of-time or JIT (ninja), with per-op compatibility checks against the
local torch/CUDA install, and a registry ``ALL_OPS`` consumed by setup.py
and ``ds_report``.  Under JAX there is nothing to compile at install time —
"ops" are jitted XLA programs and Pallas kernels compiled on first trace —
so a builder here is a *capability probe + loader*: ``is_compatible()``
answers whether this platform can run the op's fast path, and ``load()``
returns the op's entry point (triggering any lazy imports), mirroring the
reference's ``OpBuilder.load()`` contract.
"""

import hashlib
import importlib
import os
import shutil
import subprocess
import tempfile

# native sources ship inside the package (deepspeed_tpu/csrc/...) so an
# installed wheel can JIT-build them, unlike the reference's repo-root csrc/
_PKG_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_CACHE_DIR = os.environ.get(
    "DS_BUILD_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu"))


def jit_build(name, sources, extra_flags=()):
    """Compile C++ sources into a cached shared object and return its path
    — the analog of the reference's ninja JIT load
    (``op_builder/builder.py:170-220``).  Cache key = source contents +
    flags; rebuilds only when they change."""
    gxx = shutil.which("g++")
    if gxx is None:
        raise RuntimeError(f"op {name!r} needs g++ to JIT-build its native "
                           "kernel; none found on PATH")
    paths = [os.path.join(_PKG_ROOT, s) for s in sources]
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    h.update(repr(extra_flags).encode())
    # -march=native output is host-CPU-specific and $HOME may be shared
    # (NFS) across heterogeneous hosts: key the cache on toolchain + CPU
    try:
        h.update(subprocess.run([gxx, "--version"], capture_output=True,
                                text=True).stdout.encode())
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    h.update(line.encode())
                    break
    except Exception:  # dslint: disable=DSE502 -- host-fingerprint probe; a partial hash only weakens cache keying
        pass
    base_flags = ["-O3", "-shared", "-fPIC", "-std=c++17"]
    tiers = [base_flags + ["-march=native", "-fopenmp"],
             base_flags + ["-fopenmp"],
             base_flags]
    os.makedirs(_CACHE_DIR, exist_ok=True)
    last_err = None
    for tier_idx, flags in enumerate(tiers):
        out = os.path.join(
            _CACHE_DIR, f"{name}-{h.hexdigest()[:16]}-t{tier_idx}.so")
        if os.path.exists(out):
            return out
        # unique temp per process: concurrent builders (multi-process
        # launch, cold cache) must not interleave writes; os.replace makes
        # the publish atomic and last-writer-wins is fine (same content)
        fd, tmp = tempfile.mkstemp(dir=_CACHE_DIR, suffix=".so.tmp")
        os.close(fd)
        cmd = [gxx, *flags, *extra_flags, "-o", tmp, *paths]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode == 0:
            os.replace(tmp, out)
            return out
        os.unlink(tmp)
        last_err = proc.stderr
    raise RuntimeError(f"g++ failed building op {name!r}:\n{last_err}")


class OpBuilder:
    """Base op record (reference ``op_builder/builder.py:78``)."""

    NAME = "op"
    MODULE = None       # dotted path relative to deepspeed_tpu
    ENTRY = None        # attribute to return from load()

    def absolute_name(self):
        return f"deepspeed_tpu.{self.MODULE}"

    def is_compatible(self):
        ok, _ = self.compatibility()
        return ok

    def compatibility(self):
        """(ok, detail) — platform-dependent checks live in subclasses."""
        return True, "pure-XLA op (always available)"

    def load(self):
        """Import and return the op entry point (the reference's JIT-load;
        here the compile happens lazily on first trace)."""
        mod = importlib.import_module(self.absolute_name())
        return getattr(mod, self.ENTRY) if self.ENTRY else mod


def _backend():
    import jax

    return jax.default_backend()


def _has_memory(kind):
    import jax

    try:
        jax.devices()[0].memory(kind)
        return True
    except Exception:
        return False


class FusedAdamBuilder(OpBuilder):
    NAME = "fused_adam"
    MODULE = "ops.adam.fused_adam"
    ENTRY = "FusedAdam"


class FusedLambBuilder(OpBuilder):
    NAME = "fused_lamb"
    MODULE = "ops.lamb.fused_lamb"
    ENTRY = "FusedLamb"


class FlashAttentionBuilder(OpBuilder):
    NAME = "flash_attention"
    MODULE = "ops.transformer.flash_attention"
    ENTRY = "flash_attention"

    def compatibility(self):
        try:
            from jax.experimental.pallas import tpu  # noqa: F401
        except Exception:
            return False, "Pallas TPU backend not importable"
        if _backend() != "tpu":
            return False, "compiled Mosaic kernels need a TPU (interpret mode elsewhere)"
        return True, "Pallas kernel; engaged when score memory exceeds budget"


class SparseAttentionBuilder(OpBuilder):
    NAME = "sparse_attention"
    MODULE = "ops.sparse_attention"
    ENTRY = "block_sparse_attention"


class SparseFlashAttentionBuilder(OpBuilder):
    """LUT-driven Pallas block-sparse flash kernel (the reference's Triton
    SDD/DSD/DDS + softmax stack as one Mosaic kernel family)."""

    NAME = "sparse_flash_attention"
    MODULE = "ops.sparse_attention.flash_block_sparse"
    ENTRY = "flash_block_sparse_attention"

    def compatibility(self):
        try:
            from jax.experimental.pallas import tpu  # noqa: F401
        except Exception:
            return False, "Pallas TPU backend not importable"
        if _backend() != "tpu":
            return False, "compiled Mosaic kernels need a TPU (gather path elsewhere)"
        return True, "engaged for 128-multiple layout blocks (block >= 512 advised)"


class RingAttentionBuilder(OpBuilder):
    NAME = "ring_attention"
    MODULE = "ops.transformer.ring_attention"
    ENTRY = "ring_attention"


class OnebitAdamBuilder(OpBuilder):
    NAME = "onebit_adam"
    MODULE = "runtime.fp16.onebit_adam"
    ENTRY = "OnebitAdam"


class CPUAdamBuilder(OpBuilder):
    """The native host Adam kernel (reference ``csrc/adam/cpu_adam.cpp``):
    C++ (OpenMP, compiler-vectorized) JIT-built with g++, driven through
    ``jax.pure_callback``.  Pairs with the pinned_host state of
    ZeRO-Offload."""

    NAME = "cpu_adam"
    MODULE = "ops.adam.cpu_adam"
    ENTRY = "DeepSpeedCPUAdam"

    def compatibility(self):
        import shutil as _sh

        if _sh.which("g++") is None:
            return False, "g++ not found (native kernel JIT build)"
        detail = "C++ host kernel (JIT-built)"
        if not _has_memory("pinned_host"):
            detail += "; no pinned_host space — offload state stays on device"
        return True, detail


class ActivationOffloadBuilder(OpBuilder):
    NAME = "activation_offload"
    MODULE = "runtime.activation_checkpointing.checkpointing"
    ENTRY = "make_remat_policy"

    def compatibility(self):
        if not _has_memory("pinned_host"):
            return False, "no pinned_host memory space"
        if _backend() != "tpu":
            return False, "remat offload needs in-jit memory placement (TPU)"
        return True, "save_and_offload remat policy"


class TransformerBuilder(OpBuilder):
    NAME = "transformer"
    MODULE = "models.layers"
    ENTRY = "TransformerLayer"


ALL_OPS = {b.NAME: b for b in (
    FusedAdamBuilder(), FusedLambBuilder(), FlashAttentionBuilder(),
    SparseAttentionBuilder(), SparseFlashAttentionBuilder(),
    RingAttentionBuilder(), OnebitAdamBuilder(),
    CPUAdamBuilder(), ActivationOffloadBuilder(), TransformerBuilder(),
)}


def get_op_builder(name):
    return ALL_OPS[name]
