"""Op registry — the TPU analog of the reference's ``op_builder`` package.

The reference (``op_builder/builder.py:78-260``) compiles CUDA extensions
ahead-of-time or JIT (ninja), with per-op compatibility checks against the
local torch/CUDA install, and a registry ``ALL_OPS`` consumed by setup.py
and ``ds_report``.  Under JAX there is nothing to compile at install time —
"ops" are jitted XLA programs and Pallas kernels compiled on first trace —
so a builder here is a *capability probe + loader*: ``is_compatible()``
answers whether this platform can run the op's fast path, and ``load()``
returns the op's entry point (triggering any lazy imports), mirroring the
reference's ``OpBuilder.load()`` contract.
"""

import importlib


class OpBuilder:
    """Base op record (reference ``op_builder/builder.py:78``)."""

    NAME = "op"
    MODULE = None       # dotted path relative to deepspeed_tpu
    ENTRY = None        # attribute to return from load()

    def absolute_name(self):
        return f"deepspeed_tpu.{self.MODULE}"

    def is_compatible(self):
        ok, _ = self.compatibility()
        return ok

    def compatibility(self):
        """(ok, detail) — platform-dependent checks live in subclasses."""
        return True, "pure-XLA op (always available)"

    def load(self):
        """Import and return the op entry point (the reference's JIT-load;
        here the compile happens lazily on first trace)."""
        mod = importlib.import_module(self.absolute_name())
        return getattr(mod, self.ENTRY) if self.ENTRY else mod


def _backend():
    import jax

    return jax.default_backend()


def _has_memory(kind):
    import jax

    try:
        jax.devices()[0].memory(kind)
        return True
    except Exception:
        return False


class FusedAdamBuilder(OpBuilder):
    NAME = "fused_adam"
    MODULE = "ops.adam.fused_adam"
    ENTRY = "FusedAdam"


class FusedLambBuilder(OpBuilder):
    NAME = "fused_lamb"
    MODULE = "ops.lamb.fused_lamb"
    ENTRY = "FusedLamb"


class FlashAttentionBuilder(OpBuilder):
    NAME = "flash_attention"
    MODULE = "ops.transformer.flash_attention"
    ENTRY = "flash_attention"

    def compatibility(self):
        try:
            from jax.experimental.pallas import tpu  # noqa: F401
        except Exception:
            return False, "Pallas TPU backend not importable"
        if _backend() != "tpu":
            return False, "compiled Mosaic kernels need a TPU (interpret mode elsewhere)"
        return True, "Pallas kernel; engaged when score memory exceeds budget"


class SparseAttentionBuilder(OpBuilder):
    NAME = "sparse_attention"
    MODULE = "ops.sparse_attention"
    ENTRY = "block_sparse_attention"


class RingAttentionBuilder(OpBuilder):
    NAME = "ring_attention"
    MODULE = "ops.transformer.ring_attention"
    ENTRY = "ring_attention"


class OnebitAdamBuilder(OpBuilder):
    NAME = "onebit_adam"
    MODULE = "runtime.fp16.onebit_adam"
    ENTRY = "OnebitAdam"


class CPUAdamBuilder(OpBuilder):
    """ZeRO-Offload's host-resident optimizer state (the reference's
    AVX ``cpu_adam``; here a memory-space capability)."""

    NAME = "cpu_adam"
    MODULE = "runtime.zero.coordinator"
    ENTRY = "FlatParamCoordinator"

    def compatibility(self):
        if not _has_memory("pinned_host"):
            return False, "no pinned_host memory space on this backend"
        return True, "pinned_host master/optimizer state"


class ActivationOffloadBuilder(OpBuilder):
    NAME = "activation_offload"
    MODULE = "runtime.activation_checkpointing.checkpointing"
    ENTRY = "make_remat_policy"

    def compatibility(self):
        if not _has_memory("pinned_host"):
            return False, "no pinned_host memory space"
        if _backend() != "tpu":
            return False, "remat offload needs in-jit memory placement (TPU)"
        return True, "save_and_offload remat policy"


class TransformerBuilder(OpBuilder):
    NAME = "transformer"
    MODULE = "models.layers"
    ENTRY = "TransformerLayer"


ALL_OPS = {b.NAME: b for b in (
    FusedAdamBuilder(), FusedLambBuilder(), FlashAttentionBuilder(),
    SparseAttentionBuilder(), RingAttentionBuilder(), OnebitAdamBuilder(),
    CPUAdamBuilder(), ActivationOffloadBuilder(), TransformerBuilder(),
)}


def get_op_builder(name):
    return ALL_OPS[name]
