"""DeepSpeedCPUAdam: the host (C++) optimizer for ZeRO-Offload.

TPU-native take on the reference's ``DeepSpeedCPUAdam``
(``deepspeed/ops/adam/cpu_adam.py:12``, kernel
``csrc/adam/cpu_adam.cpp:21-682``): the update arithmetic runs on the HOST
CPU in a compiled C++ kernel (``csrc/adam/cpu_adam.cpp`` here, JIT-built by
the op builder with g++ — the analog of the reference's ninja JIT load),
called from inside the engine's jitted step via ``jax.pure_callback``.
With ``cpu_offload`` the master/optimizer state already lives in host
memory, so the callback round-trip moves only the gradient — the
reference's async-grad-copy + CPU-step design (``stage2.py:793-900``).

Implements the same flat-optimizer protocol as :class:`FusedAdam`, with
identical numerics (bias correction, AdamW/L2 modes) so the two are
interchangeable per config.
"""

import ctypes
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.compat import shard_map


class CPUAdamState(NamedTuple):
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray
    step: jnp.ndarray


_lib_cache = {}


def _load_kernel():
    """JIT-build csrc/adam/cpu_adam.cpp with g++ (cached .so)."""
    if "lib" in _lib_cache:
        return _lib_cache["lib"]
    from ..op_builder import jit_build

    so = jit_build("cpu_adam", ["csrc/adam/cpu_adam.cpp"])
    lib = ctypes.CDLL(so)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.ds_adam_step.argtypes = [f32p] * 7 + [
        ctypes.c_longlong] + [ctypes.c_float] * 7 + [ctypes.c_int]
    lib.ds_adam_step.restype = None
    _lib_cache["lib"] = lib
    return lib


def _host_adam(p, m, v, g, lr, beta1, beta2, wd, bc1, bc2, eps, adamw):
    lib = _load_kernel()
    p = np.ascontiguousarray(p, np.float32)
    m = np.ascontiguousarray(m, np.float32)
    v = np.ascontiguousarray(v, np.float32)
    g = np.ascontiguousarray(g, np.float32)
    p_out = np.empty_like(p)
    m_out = np.empty_like(m)
    v_out = np.empty_like(v)

    def ptr(a):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    lib.ds_adam_step(ptr(p_out), ptr(m_out), ptr(v_out), ptr(p), ptr(m),
                     ptr(v), ptr(g), p.size, float(lr), float(beta1),
                     float(beta2), float(eps), float(wd), float(bc1),
                     float(bc2), int(adamw))
    return p_out, m_out, v_out


class DeepSpeedCPUAdam:
    """Flat-space Adam whose arithmetic runs in the native host kernel."""

    name = "cpu_adam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True, adamw_mode=True,
                 adam_w_mode=None, shard_axis=None, mesh=None, **_ignored):
        _load_kernel()  # fail fast if the toolchain is unavailable
        self.bias_correction = bias_correction
        # FusedAdam spells it adam_w_mode; accept both so the optimizers
        # are interchangeable per config (reference has the same dual
        # naming between FusedAdam and DeepSpeedCPUAdam)
        self.adamw_mode = adamw_mode if adam_w_mode is None else adam_w_mode
        # set by the engine under ZeRO: the flat buffers are sharded over
        # this mesh axis and each shard must call back independently
        self.shard_axis = shard_axis
        self.mesh = mesh
        self.eps = eps
        self.param_groups = [{
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
        }]
        self.defaults = {"lr": lr, "betas": tuple(betas)}

    def init_state(self, flat_master) -> CPUAdamState:
        z = jnp.zeros_like(flat_master)
        return CPUAdamState(exp_avg=z, exp_avg_sq=z,
                            step=jnp.asarray(0, jnp.int32))

    def hyperparams(self):
        g = self.param_groups[0]
        return {
            "lr": jnp.asarray(g["lr"], jnp.float32),
            "beta1": jnp.asarray(g["betas"][0], jnp.float32),
            "beta2": jnp.asarray(g["betas"][1], jnp.float32),
            "weight_decay": jnp.asarray(g["weight_decay"], jnp.float32),
        }

    def update(self, state: CPUAdamState, flat_master, flat_grads, hp,
               segments=None, segment_ids=None):
        step = state.step + 1
        if self.bias_correction:
            tf = step.astype(jnp.float32)
            bc1 = 1.0 - hp["beta1"] ** tf
            bc2 = 1.0 - hp["beta2"] ** tf
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        eps = self.eps
        adamw = self.adamw_mode

        def host_update(p, m, v, g, lr, b1, b2, wd, c1, c2):
            sds = (jax.ShapeDtypeStruct(p.shape, jnp.float32),) * 3

            def cb(p, m, v, g, lr, b1, b2, wd, c1, c2):
                return _host_adam(p, m, v, g, lr, b1, b2, wd, c1, c2, eps,
                                  adamw)

            return jax.pure_callback(cb, sds, p, m, v, g, lr, b1, b2, wd,
                                     c1, c2)

        g32 = jnp.asarray(flat_grads, jnp.float32)
        if self.shard_axis is not None:
            # ZeRO-sharded flat buffers: one callback PER SHARD inside
            # shard_map, so no cross-device gather happens and each host
            # only touches its addressable rows (the reference's per-rank
            # partitioned CPU step, stage2.py:1416-1427)
            from jax.sharding import PartitionSpec as P

            sharded = P(self.shard_axis)
            rep = P()
            # callbacks require FULLY-manual spmd: take every mesh axis
            # manual (buffers replicate over the non-data axes)
            new_p, new_m, new_v = shard_map(
                host_update, mesh=self.mesh,
                in_specs=(sharded, sharded, sharded, sharded,
                          rep, rep, rep, rep, rep, rep),
                out_specs=(sharded, sharded, sharded),
                axis_names=set(self.mesh.axis_names), check_vma=False)(
                flat_master, state.exp_avg, state.exp_avg_sq, g32,
                hp["lr"], hp["beta1"], hp["beta2"], hp["weight_decay"],
                bc1, bc2)
        else:
            new_p, new_m, new_v = host_update(
                flat_master, state.exp_avg, state.exp_avg_sq, g32,
                hp["lr"], hp["beta1"], hp["beta2"], hp["weight_decay"],
                bc1, bc2)
        return new_p, CPUAdamState(exp_avg=new_m, exp_avg_sq=new_v, step=step)
