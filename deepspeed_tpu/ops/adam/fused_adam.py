"""Fused Adam(W) over the flat parameter space.

TPU-native equivalent of the reference's apex-style multi-tensor Adam
(``csrc/adam/multi_tensor_adam.cu:30-123``, Python wrapper
``deepspeed/ops/adam/fused_adam.py:15``): one jitted elementwise computation
updates every parameter; XLA fuses the whole chain (bias correction,
moment updates, parameter step) into a single HBM pass over the flat
buffer.  Under ZeRO the same function runs on the local shard only.
"""

from typing import NamedTuple

import jax.numpy as jnp


class AdamState(NamedTuple):
    exp_avg: jnp.ndarray      # m, f32[total]
    exp_avg_sq: jnp.ndarray   # v, f32[total]
    step: jnp.ndarray         # i32 scalar


class FusedAdam:
    """Flat-space Adam/AdamW.

    Args mirror the reference wrapper (``ops/adam/fused_adam.py:15-56``):
    ``adam_w_mode`` selects decoupled weight decay (AdamW); ``bias_correction``
    as in torch.  ``param_groups`` is a host-side facade for LR schedulers.
    """

    name = "adam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 bias_correction=True, adam_w_mode=True, amsgrad=False, **_ignored):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.bias_correction = bias_correction
        self.adam_w_mode = adam_w_mode
        self.eps = eps
        self.param_groups = [{
            "lr": lr,
            "betas": tuple(betas),
            "eps": eps,
            "weight_decay": weight_decay,
        }]
        self.defaults = {"lr": lr, "betas": tuple(betas)}

    # -- traced-state API (engine side) --
    def init_state(self, flat_master) -> AdamState:
        z = jnp.zeros_like(flat_master)
        return AdamState(exp_avg=z, exp_avg_sq=z, step=jnp.asarray(0, jnp.int32))

    def hyperparams(self):
        """Schedulable hyperparameters, read each step and passed as traced
        scalars (so LR schedules never recompile)."""
        g = self.param_groups[0]
        return {
            "lr": jnp.asarray(g["lr"], jnp.float32),
            "beta1": jnp.asarray(g["betas"][0], jnp.float32),
            "beta2": jnp.asarray(g["betas"][1], jnp.float32),
            "weight_decay": jnp.asarray(g["weight_decay"], jnp.float32),
        }

    def update(self, state: AdamState, flat_master, flat_grads, hp, segments=None,
               segment_ids=None):
        """One optimizer step on (a shard of) the flat buffer.  Pure function
        of traced inputs; called inside the engine's jitted apply."""
        lr, beta1, beta2, wd = hp["lr"], hp["beta1"], hp["beta2"], hp["weight_decay"]
        g = jnp.asarray(flat_grads, jnp.float32)
        p = flat_master
        step = state.step + 1

        if not self.adam_w_mode:
            # L2 mode (reference kernel ADAM_MODE_1): decay folded into grad.
            g = g + wd * p

        m = beta1 * state.exp_avg + (1.0 - beta1) * g
        v = beta2 * state.exp_avg_sq + (1.0 - beta2) * (g * g)

        if self.bias_correction:
            tf = step.astype(jnp.float32)
            bc1 = 1.0 - beta1 ** tf
            bc2 = 1.0 - beta2 ** tf
        else:
            bc1 = bc2 = 1.0

        denom = jnp.sqrt(v / bc2) + self.eps
        update = (m / bc1) / denom
        if self.adam_w_mode:
            # AdamW (reference kernel ADAM_MODE_0): decoupled decay.
            new_p = p - lr * (update + wd * p)
        else:
            new_p = p - lr * update
        return new_p, AdamState(exp_avg=m, exp_avg_sq=v, step=step)
