"""Communication backend: named-axis XLA collectives.

The reference scatters ~80 raw ``torch.distributed`` call sites across the
codebase (SURVEY §2.6; e.g. ``deepspeed/runtime/engine.py:836-850``,
``zero/stage2.py:727-738``).  The TPU rebuild routes *every* collective
through this one module, expressed over named mesh axes so XLA lowers them
onto ICI (intra-slice) or DCN (cross-slice) links and overlaps them with
compute via its latency-hiding scheduler — there are no streams or process
groups to manage.

Inside ``shard_map`` these are per-shard collectives over the named axis;
under plain ``jit`` + sharding annotations XLA inserts the equivalents
automatically.  Mapping from the reference's NCCL verbs:

==============================  ==========================================
reference (torch.distributed)   here (jax.lax over a named mesh axis)
==============================  ==========================================
all_reduce                      psum / pmean / pmax
reduce (to owner rank)          psum_scatter (owner = shard index)
reduce_scatter                  psum_scatter
all_gather                      all_gather
broadcast (param sync)          unnecessary under SPMD (same program+init)
broadcast (pipe p2p)            ppermute
all_to_all (sequence parallel)  all_to_all
barrier                         block_until_ready on a psum token
==============================  ==========================================
"""

from jax import lax


def psum(x, axis_name):
    """Sum-allreduce over a mesh axis (reference: dist.all_reduce SUM)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    """Mean-allreduce (reference: all_reduce followed by /= world_size)."""
    return lax.pmean(x, axis_name)


def pmax(x, axis_name):
    """Max-allreduce (reference: dist.all_reduce MAX, e.g. overflow flags)."""
    return lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return lax.pmin(x, axis_name)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    """Sum-reduce then scatter shards over the axis (reference: dist.reduce_scatter,
    ``zero/stage1.py:572`` / the ZeRO-2 reduce-to-owner pattern ``stage2.py:727``)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards from every member of the axis (reference: dist.all_gather,
    e.g. ZeRO param reassembly ``stage2.py:1444-1477``)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    """Point-to-point send/recv ring (reference: pipeline p2p as 2-rank
    broadcast groups, ``runtime/pipe/p2p.py:31-55``)."""
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    """All-to-all (no reference analog; used by Ulysses-style sequence parallelism)."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def axis_index(axis_name):
    """This shard's coordinate along the axis (reference: dist.get_rank(group))."""
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    """Size of the axis (reference: dist.get_world_size(group))."""
    from ..utils.compat import axis_size as _axis_size

    return _axis_size(axis_name)
