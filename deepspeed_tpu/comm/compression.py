"""Gradient-compression collectives: error-feedback 1-bit allreduce.

TPU-native re-design of the reference's MPI/cupy compressed allreduce
(``deepspeed/runtime/fp16/onebit_adam.py:104-228`` ``Compressed_Allreduce``
and ``runtime/custom_collectives.py``).  The algorithm is identical — each
worker sends only the sign of its (error-compensated) buffer plus one
scale; each "server" rank reduces one 1/world chunk and broadcasts the
re-compressed result — but the transport is XLA collectives over a named
mesh axis instead of mpi4py igather/allgather:

    phase 1 (worker→server):  all_to_all of packed sign chunks
                              + all_gather of worker scales
    phase 2 (server→worker):  all_gather of packed server signs + scales

Sign bits are hand-packed 8-per-uint8 before the collectives (the analog of
``cupy.packbits``), so the bytes on the wire are 1/32 of fp32 — this is the
point of the exercise on DCN-bound multi-pod meshes.  Everything is a pure
function usable inside ``shard_map`` and differentiable-free (runs in the
optimizer step, outside autodiff).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import axis_size as compat_axis_size

_BIT_WEIGHTS = np.asarray([128, 64, 32, 16, 8, 4, 2, 1], np.uint8)  # MSB-first


def pack_signs(bits):
    """[n] bool (True = +1) → [n/8] uint8, MSB-first like ``packbits``."""
    n = bits.shape[0]
    assert n % 8 == 0, f"sign buffer length {n} not divisible by 8"
    b = bits.reshape(n // 8, 8).astype(jnp.uint8)
    return (b * jnp.asarray(_BIT_WEIGHTS)).sum(-1).astype(jnp.uint8)


def unpack_signs(packed):
    """[m] uint8 → [m*8] ±1.0 float32, MSB-first."""
    bits = (packed[:, None] // jnp.asarray(_BIT_WEIGHTS, jnp.uint8)) % 2
    return bits.reshape(-1).astype(jnp.float32) * 2.0 - 1.0


def _compress(buf, error):
    """Error-feedback sign compression: returns (sign_bits_bool, scale,
    new_error).  scale = ||buf+err|| / sqrt(n); the quantization residual
    becomes the next round's error (reference ``onebit_adam.py:122-127``)."""
    comp = buf + error
    n = comp.shape[0]
    scale = jnp.linalg.norm(comp) / np.sqrt(n)
    sign_bits = comp >= 0
    signs = sign_bits.astype(jnp.float32) * 2.0 - 1.0
    new_error = comp - scale * signs
    return sign_bits, scale, new_error


def padded_size(n, world):
    """Smallest size >= ``n`` divisible by ``8*world`` — the alignment
    the packed-sign chunking needs (8 signs per uint8, one equal chunk
    per server rank).  Callers allocate their persistent error buffers
    at this size; :func:`compressed_allreduce` pads and trims the data
    buffer internally."""
    q = 8 * int(world)
    return -(-int(n) // q) * q


def compressed_allreduce(buf, worker_error, server_error, axis_name):
    """1-bit error-feedback mean-allreduce of ``buf`` over ``axis_name``.

    Args:
        buf: [n] fp32, ANY size — padded internally to
            ``padded_size(n, world)`` with zeros and trimmed on return
            (real flat-gradient sizes are rarely divisible by 8·world).
        worker_error: [padded_size(n, world)] fp32 worker residual
            (carried across steps; error feedback accumulates on the
            PADDED buffer, so its tail keeps the pad lanes' residual).
        server_error: [padded_size(n, world)/world] fp32 server residual
            for this rank's chunk.
        axis_name: mesh axis to reduce over (must be in manual shard_map).

    Returns ``(out, new_worker_error, new_server_error)`` with ``out``
    the [n] compressed approximation of ``mean(buf)`` — identical on
    all ranks; the error buffers stay padded-size.
    """
    world = compat_axis_size(axis_name)
    n = buf.shape[0]
    n_pad = padded_size(n, world)
    assert worker_error.shape[0] == n_pad, (
        f"worker_error size {worker_error.shape[0]} must be "
        f"padded_size(n={n}, world={world}) = {n_pad}")
    assert server_error.shape[0] * world == n_pad, (
        f"server_error size {server_error.shape[0]} must be "
        f"padded_size(n={n}, world={world})/world = {n_pad // world}")
    if n_pad != n:
        buf = jnp.concatenate(
            [buf, jnp.zeros((n_pad - n,), buf.dtype)])

    # -- worker compression (reference :118-127) --
    sign_bits, worker_scale, new_worker_error = _compress(buf, worker_error)

    # -- phase 1: signs chunked to server ranks (reference igather :146-165) --
    packed = pack_signs(sign_bits)  # [n_pad/8] uint8
    chunks = packed.reshape(world, n_pad // 8 // world)
    # all_to_all: rank r ends up with [world, chunk] = everyone's chunk r
    recv = jax.lax.all_to_all(chunks[None], axis_name, split_axis=1,
                              concat_axis=0, tiled=False)[:, 0]
    scales = jax.lax.all_gather(worker_scale, axis_name)  # [world]

    # -- server reduce + re-compress (reference :174-193) --
    chunk_signs = jax.vmap(unpack_signs)(recv)  # [world, n/world] ±1
    compensated = jnp.einsum("w,wn->n", scales / world, chunk_signs)
    srv_bits, server_scale, new_server_error = _compress(compensated,
                                                         server_error)

    # -- phase 2: broadcast compressed server chunks (reference :202-214) --
    srv_packed = pack_signs(srv_bits)  # [n_pad/8/world] uint8
    all_packed = jax.lax.all_gather(srv_packed, axis_name)  # [world, n_pad/8/world]
    all_scales = jax.lax.all_gather(server_scale, axis_name)  # [world]
    out_signs = jax.vmap(unpack_signs)(all_packed)  # [world, n_pad/world]
    out = (out_signs * all_scales[:, None]).reshape(n_pad)
    return out[:n], new_worker_error, new_server_error


def compressed_allreduce_reference(bufs, worker_errors, server_errors):
    """Host (numpy) simulation of the same algorithm over ``world`` buffers;
    ground truth for tests.  Returns (out, new_worker_errors,
    new_server_errors)."""
    bufs = [np.asarray(b, np.float64) for b in bufs]
    world = len(bufs)
    n = bufs[0].shape[0]
    signs, scales, new_werrs = [], [], []
    for b, e in zip(bufs, worker_errors):
        comp = b + np.asarray(e, np.float64)
        scale = np.linalg.norm(comp) / np.sqrt(n)
        s = np.where(comp >= 0, 1.0, -1.0)
        new_werrs.append(comp - scale * s)
        signs.append(s)
        scales.append(scale)
    chunk = n // world
    outs, new_serrs = [], []
    for r in range(world):
        comp = sum(scales[w] / world * signs[w][r * chunk:(r + 1) * chunk]
                   for w in range(world))
        comp = comp + np.asarray(server_errors[r], np.float64)
        sscale = np.linalg.norm(comp) / np.sqrt(chunk)
        ss = np.where(comp >= 0, 1.0, -1.0)
        new_serrs.append(comp - sscale * ss)
        outs.append(sscale * ss)
    return np.concatenate(outs), new_werrs, new_serrs
