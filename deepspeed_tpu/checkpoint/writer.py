"""Atomic on-disk commit protocol + integrity verification.

Commit order for one checkpoint:

1. payload files are written into ``<tag>.tmp/`` and fsynced one by one;
2. ``manifest.json`` (per-file byte sizes + checksums) is written LAST and
   fsynced — a tmp dir without a readable manifest is by definition torn;
3. ``os.replace(<tag>.tmp, <tag>)`` publishes the directory atomically;
4. the ``latest`` pointer is swapped through its own tmp + ``os.replace``.

A crash at any point leaves either the previous committed checkpoint (plus
a stale ``*.tmp`` dir that :func:`verify_checkpoint` rejects and retention
sweeps) or the new one — never a loadable half-write.

Checksums prefer hardware crc32c when the optional ``crc32c`` package is
present and fall back to zlib's crc32; the manifest records which
algorithm produced its values and verification always recomputes with
that algorithm (degrading to sizes-only when it isn't available locally).
"""

import json
import os
import shutil
import zlib

from ..utils.logging import logger
from .constants import (LATEST_FILE, MANIFEST_FORMAT_VERSION, MANIFEST_JSON,
                        META_JSON, OLD_SUFFIX, TMP_SUFFIX)

# checksum updaters by manifest name; zlib crc32 is always available,
# hardware crc32c only when the optional wheel exists.  Writers use the
# best local algorithm; verifiers MUST use the manifest's algorithm (a
# crc32 manifest checked with crc32c would flag every intact file)
_CRC_UPDATERS = {"crc32": zlib.crc32}
try:  # gated optional dep
    import crc32c as _crc32c_mod

    _CRC_UPDATERS["crc32c"] = _crc32c_mod.crc32c
    _CRC_ALGORITHM = "crc32c"
except ImportError:
    _CRC_ALGORITHM = "crc32"


class CheckpointError(RuntimeError):
    """Base error for checkpoint save/load failures."""


class CheckpointCorruptionError(CheckpointError):
    """A checkpoint directory failed manifest/integrity verification."""


# Test seam: called as hook(tmp_dir, filename) after each payload file is
# durably written.  Crash-mid-save tests raise from it; async-overlap
# tests block on an event in it.  Never set in production.
_file_written_hook = None


def file_checksum(path, chunk_bytes=4 * 1024 * 1024, algorithm=None):
    update = _CRC_UPDATERS[algorithm or _CRC_ALGORITHM]
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            crc = update(chunk, crc)
    return crc & 0xFFFFFFFF


def _checksum_fn(name):
    if name not in _CRC_UPDATERS:
        return None  # manifest written with an algorithm we don't have
    return lambda path: file_checksum(path, algorithm=name)


def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; replace still lands
    finally:
        os.close(fd)


def write_file(path, writer_fn):
    """Write one payload file durably: ``writer_fn(file_object)`` then
    flush + fsync before close."""
    with open(path, "wb") as f:
        writer_fn(f)
        f.flush()
        os.fsync(f.fileno())


def write_checkpoint(save_dir, tag, file_writers, extra_manifest=None):
    """Write + atomically commit one checkpoint; returns the final dir.

    ``file_writers`` maps filename -> ``fn(file_object)``; files are
    written in mapping order.  Raises on any I/O failure — the caller
    (manager) owns retry policy.  An existing ``<tag>/`` is replaced only
    at the final ``os.replace``, so a failed re-save never clobbers it.
    """
    save_dir = str(save_dir)
    final_dir = os.path.join(save_dir, str(tag))
    tmp_dir = final_dir + TMP_SUFFIX
    if os.path.isdir(tmp_dir):  # stale leftovers from a crashed attempt
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)

    entries = {}
    for name, writer_fn in file_writers.items():
        path = os.path.join(tmp_dir, name)
        write_file(path, writer_fn)
        entries[name] = {"bytes": os.path.getsize(path),
                         "checksum": file_checksum(path)}
        if _file_written_hook is not None:
            _file_written_hook(tmp_dir, name)

    manifest = {"format_version": MANIFEST_FORMAT_VERSION,
                "tag": str(tag),
                "checksum_algorithm": _CRC_ALGORITHM,
                "files": entries}
    if extra_manifest:
        manifest.update(extra_manifest)
    write_file(os.path.join(tmp_dir, MANIFEST_JSON),
               lambda f: f.write(json.dumps(manifest, indent=2).encode()))
    _fsync_path(tmp_dir)

    if os.path.isdir(final_dir):
        # re-saving an existing tag: move the old dir aside first so the
        # window without a committed <tag>/ is one rename, not a full
        # rewrite (os.replace cannot overwrite a non-empty dir).  A crash
        # inside that window is healed by recover_tag on the next load.
        doomed = final_dir + OLD_SUFFIX
        if os.path.isdir(doomed):
            shutil.rmtree(doomed)
        os.replace(final_dir, doomed)
        os.replace(tmp_dir, final_dir)
        shutil.rmtree(doomed, ignore_errors=True)
    else:
        os.replace(tmp_dir, final_dir)
    _fsync_path(save_dir)
    return final_dir


def recover_tag(save_dir, tag):
    """Heal a crash that hit a same-tag re-save between its two renames:
    if ``<tag>/`` is missing but a manifest-complete ``<tag>.old/``
    survives, rename it back.  Returns True if a recovery happened."""
    final_dir = os.path.join(str(save_dir), str(tag))
    old_dir = final_dir + OLD_SUFFIX
    if os.path.isdir(final_dir) or not os.path.isdir(old_dir):
        return False
    status, _ = verify_checkpoint(old_dir)
    if status not in ("ok", "legacy"):  # legacy: manifest-less but intact
        return False
    os.replace(old_dir, final_dir)
    _fsync_path(str(save_dir))
    logger.warning(f"recovered checkpoint {final_dir} from interrupted "
                   f"re-save ({OLD_SUFFIX} fallback)")
    return True


def write_latest(save_dir, tag):
    """Atomically point ``latest`` at ``tag`` (tmp + ``os.replace``)."""
    latest = os.path.join(str(save_dir), LATEST_FILE)
    tmp = latest + TMP_SUFFIX
    write_file(tmp, lambda f: f.write(str(tag).encode()))
    os.replace(tmp, latest)
    _fsync_path(str(save_dir))


def read_latest(save_dir):
    """Tag named by the ``latest`` pointer, or None."""
    latest = os.path.join(str(save_dir), LATEST_FILE)
    if not os.path.isfile(latest):
        return None
    with open(latest) as f:
        return f.read().strip() or None


def read_manifest(ckpt_dir):
    path = os.path.join(str(ckpt_dir), MANIFEST_JSON)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def verify_checkpoint(ckpt_dir, check_checksums=True):
    """Integrity-check one checkpoint directory.

    Returns ``(status, problems)`` where status is:

    - ``"ok"``      manifest present, every file matches size (+checksum);
    - ``"legacy"``  pre-manifest layout (``meta.json`` but no manifest) —
      loadable for back-compat, nothing to verify against;
    - ``"bad"``     torn/corrupt: missing dir, a ``*.tmp`` dir, unreadable
      manifest, or any file missing / size or checksum mismatch.
    """
    ckpt_dir = str(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        return "bad", [f"{ckpt_dir} is not a directory"]
    if ckpt_dir.rstrip(os.sep).endswith(TMP_SUFFIX):
        return "bad", [f"{ckpt_dir} is an uncommitted {TMP_SUFFIX} dir"]
    try:
        manifest = read_manifest(ckpt_dir)
    except (json.JSONDecodeError, OSError) as e:
        return "bad", [f"unreadable {MANIFEST_JSON}: {e}"]
    if manifest is None:
        if os.path.isfile(os.path.join(ckpt_dir, META_JSON)):
            return "legacy", []
        return "bad", [f"no {MANIFEST_JSON} and no {META_JSON}"]

    problems = []
    checksum_fn = _checksum_fn(manifest.get("checksum_algorithm", ""))
    for name, entry in manifest.get("files", {}).items():
        path = os.path.join(ckpt_dir, name)
        if not os.path.isfile(path):
            problems.append(f"missing file {name}")
            continue
        size = os.path.getsize(path)
        if size != entry.get("bytes"):
            problems.append(
                f"{name}: size {size} != manifest {entry.get('bytes')}")
            continue
        if check_checksums:
            if checksum_fn is None:
                logger.warning(
                    f"checkpoint {ckpt_dir}: manifest checksums use "
                    f"{manifest.get('checksum_algorithm')!r} which is not "
                    f"available here; verifying sizes only")
                checksum_fn = False
            if checksum_fn:
                crc = checksum_fn(path)
                if crc != entry.get("checksum"):
                    problems.append(
                        f"{name}: checksum {crc:#010x} != manifest "
                        f"{entry.get('checksum', 0):#010x}")
    return ("ok" if not problems else "bad"), problems
