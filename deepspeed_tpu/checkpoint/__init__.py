"""Async fault-tolerant checkpoint subsystem.

Three layers (docs/checkpointing.md has the full protocol):

- :mod:`.snapshot` — one blocking device->host gather producing an
  immutable :class:`CheckpointSnapshot`;
- :mod:`.writer` — the atomic commit protocol (``<tag>.tmp/`` + fsync +
  manifest checksums + ``os.replace``) and :func:`verify_checkpoint`;
- :mod:`.manager` — :class:`CheckpointManager`: background writer threads,
  retention (``keep_last_n`` / ``keep_every_n_steps``), retry/backoff, and
  the SIGTERM preemption drain.

``engine.save_checkpoint`` / ``load_checkpoint`` are thin wrappers over
these; the ``"checkpoint": {...}`` config block selects the behavior.
"""

from .config import DeepSpeedCheckpointConfig  # noqa: F401
from .constants import (CLIENT_STATE_PKL, LATEST_FILE, MANIFEST_JSON,  # noqa: F401
                        META_JSON, MODEL_STATES_NPZ, OPTIM_STATES_NPZ,
                        TMP_SUFFIX)
from .manager import CheckpointManager, drain_inflight  # noqa: F401
from .snapshot import (CheckpointSnapshot, capture_engine_snapshot,  # noqa: F401
                       load_model_states)
from .writer import (CheckpointCorruptionError, CheckpointError,  # noqa: F401
                     read_latest, read_manifest, recover_tag,
                     verify_checkpoint, write_checkpoint, write_latest)
