"""Checkpoint file-layout names shared by every writer/reader.

The on-disk layout (SURVEY §3.5 for the reference's analog)::

    <save_dir>/
      latest                    tag of the newest COMMITTED checkpoint
      <tag>/                    a committed checkpoint (atomic os.replace)
        manifest.json           sizes + checksums of every payload file
        model_states.npz        params in NATIVE dtype (dtype map in meta)
        zero_optim_states.npz   unpadded flat master + optimizer leaves
        meta.json               step counters, scale state, dtype map, ...
        client_state.pkl        optional user blob
      <tag>.tmp/                in-progress write; never loadable
"""

MODEL_STATES_NPZ = "model_states.npz"
OPTIM_STATES_NPZ = "zero_optim_states.npz"
META_JSON = "meta.json"
CLIENT_STATE_PKL = "client_state.pkl"
LATEST_FILE = "latest"
MANIFEST_JSON = "manifest.json"
TMP_SUFFIX = ".tmp"
# previous committed dir parked aside during a same-tag re-save; recovered
# (renamed back) on load if a crash hit the one-rename window
OLD_SUFFIX = ".old"
MANIFEST_FORMAT_VERSION = 1
