"""CheckpointManager: async commits, retention, retry, preemption drain.

One manager per engine.  ``save()`` takes an already-captured
:class:`~deepspeed_tpu.checkpoint.snapshot.CheckpointSnapshot` and either
commits it inline (sync) or on a background thread (async) so
``train_batch`` resumes immediately after the host gather.  Commits to the
same directory serialize on a per-directory lock, and every in-flight
async save is tracked in a module-level registry so loaders (including a
different engine in the same process) can :func:`drain_inflight` before
resolving ``latest``.

Writer threads are non-daemon on purpose: a normal interpreter exit waits
for the last commit instead of tearing a checkpoint.
"""

import os
import shutil
import signal
import threading
import time
import weakref

from ..utils.logging import log_dist, logger
from . import writer
from .constants import META_JSON, OLD_SUFFIX, TMP_SUFFIX

# RLocks throughout: the preemption handler runs ON the main thread and
# may interrupt a sync commit that already holds the dir/registry lock —
# a plain Lock would deadlock the final save
_REGISTRY_LOCK = threading.RLock()
_INFLIGHT = {}    # realpath(save_dir) -> [Thread, ...]
_DIR_LOCKS = {}   # realpath(save_dir) -> RLock (commit serialization)
# module-global like the locks: the monotonic-`latest` guard must hold
# across every manager/engine in the process writing the same dir
_COMMITTED_STEPS = {}   # realpath(save_dir) -> newest committed step

# monotonic deadline set while the preemption handler runs: commits must
# not block indefinitely on a dir lock a hung writer thread still holds
_PREEMPT_DEADLINE = None


def _dir_key(save_dir):
    return os.path.realpath(str(save_dir))


def _dir_lock(save_dir):
    with _REGISTRY_LOCK:
        return _DIR_LOCKS.setdefault(_dir_key(save_dir), threading.RLock())


# preemption-handler state: one OS-level handler per process; callbacks
# are weakrefs for bound methods (dead engines drop out) or thunks for
# plain functions
_PREEMPT_CALLBACKS = []   # [ref()] -> final_save_fn or None when dead
_PREEMPT_PREVIOUS = {}    # signum -> disposition we replaced


def _arm_drain_watchdog(grace):
    """Hard deadline on the WHOLE preemption drain + final save.

    The lock acquires below are individually bounded, but the final
    save's actual payload write is not — stuck storage (a wedged NFS
    mount, a dead remote filesystem) can pin ``fn()`` mid-``write()``
    far past every lock timeout.  Without this, the process sits in the
    hung syscall until the launcher's SIGKILL at the END of the full
    kill grace, and the exit reads as an unhandled signal death.  The
    watchdog turns that into a deliberate, RESPAWNABLE hang exit
    (:data:`~deepspeed_tpu.resilience.constants.EXIT_STEP_HANG`): the
    supervisor reads lost capacity and respawns/resizes immediately
    instead of waiting out the grace.

    Deadline: ``DS_TERM_DRAIN_DEADLINE_SECS`` (<= 0 disables), default
    90% of the kill grace — inside the window the launcher would have
    SIGKILLed us anyway, so arming it never loses a save that would
    have landed.  Returns the armed timer (cancel on normal handler
    completion), or None when disabled."""
    raw = os.environ.get("DS_TERM_DRAIN_DEADLINE_SECS", "")
    try:
        secs = float(raw) if raw else grace * 0.9
    except ValueError:
        # this runs INSIDE the SIGTERM handler: a malformed env value
        # must degrade to the default, never abort the drain + final
        # save it exists to protect
        logger.warning(
            f"DS_TERM_DRAIN_DEADLINE_SECS={raw!r} is not a number; "
            f"using the default (90% of the kill grace)")
        secs = grace * 0.9
    if secs <= 0:
        return None

    def fire():
        from ..resilience.constants import EXIT_STEP_HANG

        logger.error(
            f"preemption drain still running at the hard deadline "
            f"({secs:.1f}s): the checkpoint writer itself is hung; "
            f"exiting {EXIT_STEP_HANG} (respawnable) instead of pinning "
            "the process until the launcher's SIGKILL")
        os._exit(EXIT_STEP_HANG)

    timer = threading.Timer(secs, fire)
    timer.daemon = True
    timer.start()
    return timer


def _preemption_handler(signum, frame):
    global _PREEMPT_DEADLINE
    logger.warning(f"signal {signum}: draining checkpoint writes and "
                   "taking a final synchronous checkpoint")
    _PREEMPT_CALLBACKS[:] = [r for r in _PREEMPT_CALLBACKS
                             if r() is not None]
    # bounded drain: a writer queued on a dir RLock the interrupted main
    # thread owns can never finish while we join it — time-box to a slice
    # of the launcher's kill grace and let the final save (which CAN
    # re-enter that RLock) use the rest
    try:
        grace = float(os.environ.get("DS_TERM_GRACE_SECS", "30"))
    except ValueError:
        # inside the SIGTERM handler: a malformed env value must never
        # abort the drain + final save (same contract as the drain
        # watchdog's own env parse below)
        logger.warning(
            f"DS_TERM_GRACE_SECS="
            f"{os.environ.get('DS_TERM_GRACE_SECS')!r} is not a "
            f"number; using 30")
        grace = 30.0
    drain_watchdog = _arm_drain_watchdog(grace)
    try:
        if not drain_inflight(timeout=grace / 3):
            logger.warning("preemption drain timed out; proceeding to the "
                           "final synchronous checkpoint")
    except Exception as e:  # noqa: BLE001 — dying anyway; say why
        logger.error(f"preemption drain failed: {e}")
    # a writer that survived the drain may still HOLD a dir lock (stuck
    # storage); bound the final save's lock acquire so it skips with an
    # error instead of pinning the process until the launcher's SIGKILL
    _PREEMPT_DEADLINE = time.monotonic() + grace / 2
    try:
        for ref in reversed(_PREEMPT_CALLBACKS):  # newest engine first
            fn = ref()
            if fn is None:
                continue
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — dying anyway; say why
                logger.error(f"preemption checkpoint failed: {e}")
    finally:
        _PREEMPT_DEADLINE = None
        if drain_watchdog is not None:
            drain_watchdog.cancel()
    prev = _PREEMPT_PREVIOUS.get(signum)
    if callable(prev):
        prev(signum, frame)
    else:
        # SIG_DFL/SIG_IGN, or None (installed outside python): restore
        # and re-deliver so shutdown proceeds under that disposition
        signal.signal(signum, signal.SIG_DFL if prev is None else prev)
        signal.raise_signal(signum)


def drain_inflight(save_dir=None, timeout=None):
    """Join pending async saves (for ``save_dir``, or all).  Returns True
    if everything drained within ``timeout``."""
    with _REGISTRY_LOCK:
        if save_dir is None:
            threads = [t for ts in _INFLIGHT.values() for t in ts]
        else:
            threads = list(_INFLIGHT.get(_dir_key(save_dir), ()))
    deadline = None if timeout is None else time.monotonic() + timeout
    for t in threads:
        t.join(None if deadline is None
               else max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            return False
    return True


class CheckpointManager:
    """Owns the write side of the checkpoint subsystem for one engine."""

    def __init__(self, config=None):
        from .config import DeepSpeedCheckpointConfig

        self.config = config or DeepSpeedCheckpointConfig({})
        self.last_error = None            # last failed commit's exception
        self._errors = {}                 # dir key -> last failed commit
        # optional TelemetryManager (engine-injected; this module never
        # imports telemetry): checkpoint lifecycle events — queue depth,
        # commit latency/bytes/retries, failures — emitted from the save
        # path and the background writer threads (sinks are thread-safe)
        self.telemetry = None

    def _emit(self, event_type, step=None, **data):
        if self.telemetry is not None:
            self.telemetry.emit(event_type, step=step, **data)

    # ------------------------------------------------------------- save
    def save(self, snapshot, save_dir, async_save=None):
        """Commit ``snapshot`` under ``save_dir``; returns True if the
        commit succeeded (async saves return True optimistically — check
        ``last_error`` / ``wait()`` for the outcome)."""
        if async_save is None:
            async_save = self.config.async_save
        prior = self._errors.get(_dir_key(save_dir))
        if prior is not None:
            # async failures are otherwise only visible via wait(): keep
            # shouting on every subsequent save so a disk-full job cannot
            # run to completion having silently written zero checkpoints
            logger.error(f"previous checkpoint save to {save_dir} FAILED "
                         f"({prior}); call engine.wait_checkpoint() to "
                         "turn async saves into a durable guarantee")
        if not async_save:
            return self._commit(snapshot, save_dir)

        key = _dir_key(save_dir)
        thread = threading.Thread(
            target=self._commit_tracked, args=(snapshot, save_dir),
            name=f"ckpt-writer-{snapshot.tag}", daemon=False)
        # register + start under one lock so drain_inflight can never
        # snapshot (and try to join) a not-yet-started thread
        with _REGISTRY_LOCK:
            _INFLIGHT.setdefault(key, []).append(thread)
            depth = len(_INFLIGHT[key])
            try:
                thread.start()
            except Exception:
                _INFLIGHT[key].remove(thread)
                raise
        self._emit("ckpt_queued", step=snapshot.global_steps,
                   tag=str(snapshot.tag), queue_depth=depth)
        if self.telemetry is not None:
            self.telemetry.gauge("ckpt/queue_depth").set(depth)
        return True

    def wait(self, save_dir=None, timeout=None):
        """Drain this process's pending async saves; raise if the most
        recent commit for ``save_dir`` (or, with no dir, for any dir this
        manager saved to) failed."""
        ok = drain_inflight(save_dir, timeout)
        if save_dir is None:
            errors = list(self._errors.values())
        else:
            err = self._errors.get(_dir_key(save_dir))
            errors = [err] if err is not None else []
        if errors:
            raise writer.CheckpointError(
                f"async checkpoint save failed: {errors[-1]}"
            ) from errors[-1]
        return ok

    def _commit_tracked(self, snapshot, save_dir):
        try:
            self._commit(snapshot, save_dir)
        finally:
            with _REGISTRY_LOCK:
                threads = _INFLIGHT.get(_dir_key(save_dir), [])
                threads[:] = [t for t in threads
                              if t is not threading.current_thread()]
                depth = len(threads)
            if self.telemetry is not None:
                # drain side of the queue-depth gauge: without this the
                # last enqueue's depth sticks in every later snapshot and
                # reads as a permanently stuck writer
                self.telemetry.gauge("ckpt/queue_depth").set(depth)

    def _commit(self, snapshot, save_dir):
        lock = _dir_lock(save_dir)
        deadline = _PREEMPT_DEADLINE
        if deadline is not None:
            # preemption final save: never block past the kill grace on a
            # lock a hung writer thread may hold (reentrant main-thread
            # acquisition still succeeds instantly)
            if not lock.acquire(timeout=max(0.0,
                                            deadline - time.monotonic())):
                e = writer.CheckpointError(
                    f"checkpoint {snapshot.tag} skipped: dir lock for "
                    f"{save_dir} still held at the preemption deadline")
                self.last_error = e
                self._errors[_dir_key(save_dir)] = e
                logger.error(str(e))
                return False
        else:
            lock.acquire()
        try:
            return self._commit_locked(snapshot, save_dir)
        finally:
            lock.release()

    def _commit_locked(self, snapshot, save_dir):
        attempts = self.config.save_retries + 1
        final_dir = None
        t_commit0 = time.monotonic()
        retries_used = 0
        for attempt in range(attempts):
            try:
                final_dir = writer.write_checkpoint(
                    save_dir, snapshot.tag, snapshot.file_writers(),
                    extra_manifest=snapshot.manifest_extra())
                retries_used = attempt
                break
            except Exception as e:  # noqa: BLE001 — retry any I/O error
                if attempt + 1 >= attempts:
                    self.last_error = e
                    self._errors[_dir_key(save_dir)] = e
                    logger.error(
                        f"checkpoint {snapshot.tag} failed after "
                        f"{attempts} attempt(s): {e}")
                    self._commit_failed_telemetry(snapshot, e)
                    return False
                backoff = self.config.retry_backoff_secs * (2 ** attempt)
                logger.warning(
                    f"checkpoint {snapshot.tag} attempt "
                    f"{attempt + 1}/{attempts} failed ({e}); retrying "
                    f"in {backoff:.1f}s")
                time.sleep(backoff)

        key = _dir_key(save_dir)
        step = snapshot.global_steps
        try:
            if writer.read_latest(save_dir) is None:
                # no `latest` on disk: the dir was wiped or is brand new —
                # a stale guard from a previous run must not pin it
                _COMMITTED_STEPS.pop(key, None)
            # an out-of-order late commit must not move `latest` (or the
            # retention window) backwards past a newer checkpoint
            if snapshot.save_latest and step >= _COMMITTED_STEPS.get(
                    key, -1):
                writer.write_latest(save_dir, snapshot.tag)
        except Exception as e:  # noqa: BLE001 — surface via wait()
            self.last_error = e
            self._errors[key] = e
            logger.error(f"checkpoint {snapshot.tag} committed but "
                         f"'latest' pointer update failed: {e}")
            self._commit_failed_telemetry(snapshot, e)
            return False
        if snapshot.save_latest:
            # save_latest=False commits (archival tags) must not pin the
            # guard: a later lower-step save that DOES want `latest` moved
            # would otherwise be silently skipped
            _COMMITTED_STEPS[key] = max(step, _COMMITTED_STEPS.get(key, -1))
        self._errors.pop(key, None)
        self.last_error = None
        try:
            self._apply_retention(save_dir)
        except Exception as e:  # noqa: BLE001 — the save itself landed
            logger.warning(f"retention sweep after {snapshot.tag} "
                           f"failed (checkpoint is committed): {e}")
        self._commit_ok_telemetry(snapshot, final_dir,
                                  time.monotonic() - t_commit0,
                                  retries_used)
        log_dist(f"saved checkpoint {final_dir}", ranks=[0])
        return True

    # --------------------------------------------------------- telemetry
    def _commit_ok_telemetry(self, snapshot, final_dir, latency_secs,
                             retries):
        if self.telemetry is None:
            return
        total_bytes = 0
        try:
            manifest = writer.read_manifest(final_dir)
            if manifest:
                total_bytes = sum(
                    int(e.get("bytes", 0))
                    for e in manifest.get("files", {}).values())
        except (OSError, ValueError) as e:
            logger.warning("telemetry: unreadable manifest under "
                           f"{final_dir}: {e}")
        self._emit("ckpt_commit", step=snapshot.global_steps,
                   tag=str(snapshot.tag), latency_secs=float(latency_secs),
                   bytes=total_bytes, retries=int(retries))
        self.telemetry.counter("ckpt/commits").inc()
        self.telemetry.counter("ckpt/bytes_written").inc(total_bytes)
        if retries:
            self.telemetry.counter("ckpt/retries").inc(retries)
        self.telemetry.histogram("ckpt/commit_latency_secs").observe(
            latency_secs)

    def _commit_failed_telemetry(self, snapshot, error):
        if self.telemetry is None:
            return
        self._emit("ckpt_failed", step=snapshot.global_steps,
                   tag=str(snapshot.tag), error=str(error))
        self.telemetry.counter("ckpt/failures").inc()

    # -------------------------------------------------------- retention
    def _list_committed(self, save_dir):
        """[(step, tag)] for every committed checkpoint dir under
        ``save_dir`` (manifest step, falling back to meta.json, then -1)."""
        out = []
        try:
            names = os.listdir(save_dir)
        except OSError:
            return out
        for name in names:
            path = os.path.join(save_dir, name)
            if (not os.path.isdir(path) or name.endswith(TMP_SUFFIX)
                    or name.endswith(OLD_SUFFIX)):
                continue
            step = None
            try:
                manifest = writer.read_manifest(path)
                if manifest is not None:
                    step = manifest.get("global_steps")
                elif os.path.isfile(os.path.join(path, META_JSON)):
                    import json

                    with open(os.path.join(path, META_JSON)) as f:
                        step = json.load(f).get("global_steps")
                else:
                    continue  # not a checkpoint dir; never touch it
            except (OSError, ValueError):
                continue
            out.append((int(step) if step is not None else -1, name))
        return out

    def _apply_retention(self, save_dir):
        """Prune committed checkpoints down to the configured policy and
        sweep stale ``*.tmp`` dirs.  Runs under the dir lock right after a
        successful commit, so any tmp dir present is a dead write."""
        for name in os.listdir(save_dir):
            path = os.path.join(save_dir, name)
            if name.endswith(TMP_SUFFIX):
                (shutil.rmtree if os.path.isdir(path) else os.remove)(path)
            elif name.endswith(OLD_SUFFIX) and os.path.isdir(path):
                # parked-aside dir from a same-tag re-save: recover it if
                # its final dir is gone (interrupted re-save), else it is
                # superseded and dead
                tag = name[:-len(OLD_SUFFIX)]
                if not writer.recover_tag(save_dir, tag):
                    shutil.rmtree(path, ignore_errors=True)

        n = self.config.keep_last_n
        if n <= 0:
            return
        committed = sorted(self._list_committed(save_dir))
        latest_tag = writer.read_latest(save_dir)
        every = self.config.keep_every_n_steps
        keep = {tag for _, tag in committed[-n:]}
        if latest_tag:
            keep.add(latest_tag)
        if every > 0:
            keep.update(tag for step, tag in committed
                        if step >= 0 and step % every == 0)
        for _, tag in committed:
            if tag not in keep:
                shutil.rmtree(os.path.join(save_dir, tag),
                              ignore_errors=True)
                log_dist(f"retention: pruned checkpoint {tag}", ranks=[0])

    # ------------------------------------------------------- preemption
    def install_preemption_handler(self, final_save_fn,
                                   signals=(signal.SIGTERM,)):
        """On SIGTERM (TPU preemption notice), drain in-flight saves, run
        one final SYNCHRONOUS ``final_save_fn()``, then re-deliver the
        signal to the previous disposition so shutdown proceeds.  Only
        callable from the main thread; chained handlers are preserved.

        One OS-level handler is installed per process no matter how many
        engines register: callbacks go into a module-level list, bound
        methods as weakrefs so a discarded engine neither leaks nor gets
        a pointless final checkpoint on preemption."""
        if threading.current_thread() is not threading.main_thread():
            logger.warning("preemption handler not installed: signal "
                           "handlers require the main thread")
            return False

        try:
            ref = weakref.WeakMethod(final_save_fn)
        except TypeError:  # plain function/lambda: hold it strongly
            ref = (lambda f=final_save_fn: f)
        _PREEMPT_CALLBACKS.append(ref)

        for sig in signals:
            # (re)install only if something else holds the disposition —
            # installing our own handler over itself would self-chain
            current = signal.getsignal(sig)
            if current is not _preemption_handler:
                _PREEMPT_PREVIOUS[sig] = current
                signal.signal(sig, _preemption_handler)
        return True
