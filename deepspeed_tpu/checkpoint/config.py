"""Typed view of the ``"checkpoint": {...}`` config block.

Parsed by :class:`~deepspeed_tpu.runtime.config.DeepSpeedConfig` alongside
the other feature subsections; consumed by the engine and
:class:`~deepspeed_tpu.checkpoint.manager.CheckpointManager`.
"""

from ..runtime import constants as C
from ..runtime.config_utils import get_scalar_param


class DeepSpeedCheckpointConfig:
    def __init__(self, param_dict=None):
        ckpt = (param_dict or {}).get(C.CHECKPOINT, {})
        self.async_save = bool(get_scalar_param(
            ckpt, C.CHECKPOINT_ASYNC_SAVE, C.CHECKPOINT_ASYNC_SAVE_DEFAULT))
        self.keep_last_n = int(get_scalar_param(
            ckpt, C.CHECKPOINT_KEEP_LAST_N, C.CHECKPOINT_KEEP_LAST_N_DEFAULT))
        self.keep_every_n_steps = int(get_scalar_param(
            ckpt, C.CHECKPOINT_KEEP_EVERY_N_STEPS,
            C.CHECKPOINT_KEEP_EVERY_N_STEPS_DEFAULT))
        self.verify_on_load = bool(get_scalar_param(
            ckpt, C.CHECKPOINT_VERIFY_ON_LOAD,
            C.CHECKPOINT_VERIFY_ON_LOAD_DEFAULT))
        self.save_retries = int(get_scalar_param(
            ckpt, C.CHECKPOINT_SAVE_RETRIES, C.CHECKPOINT_SAVE_RETRIES_DEFAULT))
        self.retry_backoff_secs = float(get_scalar_param(
            ckpt, C.CHECKPOINT_RETRY_BACKOFF_SECS,
            C.CHECKPOINT_RETRY_BACKOFF_SECS_DEFAULT))
        self.save_on_preemption = bool(get_scalar_param(
            ckpt, C.CHECKPOINT_SAVE_ON_PREEMPTION,
            C.CHECKPOINT_SAVE_ON_PREEMPTION_DEFAULT))

        assert self.keep_last_n >= 0, (
            f"checkpoint.{C.CHECKPOINT_KEEP_LAST_N} must be >= 0")
        assert self.keep_every_n_steps >= 0, (
            f"checkpoint.{C.CHECKPOINT_KEEP_EVERY_N_STEPS} must be >= 0")
        assert self.save_retries >= 0, (
            f"checkpoint.{C.CHECKPOINT_SAVE_RETRIES} must be >= 0")
        assert self.retry_backoff_secs >= 0, (
            f"checkpoint.{C.CHECKPOINT_RETRY_BACKOFF_SECS} must be >= 0")

    def __repr__(self):
        return (f"DeepSpeedCheckpointConfig(async_save={self.async_save}, "
                f"keep_last_n={self.keep_last_n}, "
                f"keep_every_n_steps={self.keep_every_n_steps}, "
                f"verify_on_load={self.verify_on_load}, "
                f"save_retries={self.save_retries}, "
                f"retry_backoff_secs={self.retry_backoff_secs}, "
                f"save_on_preemption={self.save_on_preemption})")
