"""Host-side checkpoint snapshots.

:func:`capture_engine_snapshot` performs the device->host gather ONCE (the
only part of a save that must block training) and returns an immutable
:class:`CheckpointSnapshot` of plain numpy arrays + JSON-able metadata that
a background writer thread can serialize without touching live engine
state.  Client state is pickled eagerly for the same reason.

Model states are stored in their NATIVE dtype (a bf16 run no longer pays a
2x fp32 checkpoint-size tax).  Non-numpy-native dtypes (bfloat16, fp8) are
stored as same-width unsigned-int views with the true dtype recorded under
``model_dtypes`` in ``meta.json`` and the manifest;
:func:`load_model_states` reverses this, and old all-fp32 checkpoints
(no dtype map) pass through unchanged.
"""

import json
import pickle

import jax
import numpy as np

from ..runtime.utils import tree_path_key
from .constants import (CLIENT_STATE_PKL, META_JSON, MODEL_STATES_NPZ,
                        OPTIM_STATES_NPZ)

# dtypes np.savez round-trips faithfully; anything else (ml_dtypes
# extension types) is stored as a same-width uint view + a dtype record
_NPZ_NATIVE = frozenset(
    "float16 float32 float64 int8 int16 int32 int64 "
    "uint8 uint16 uint32 uint64 bool complex64 complex128".split())
_WIDTH_VIEW = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def ensure_owned(arr):
    """Host array -> numpy array that OWNS its memory.

    On CPU backends ``jax.device_get`` can return a zero-copy view of a
    buffer the next (donating) step overwrites mid-async-write; TPU
    transfers already materialize a fresh owning array, so only views get
    the extra copy."""
    arr = np.asarray(arr)
    if arr.base is None and arr.flags.owndata:
        return arr
    return np.array(arr, copy=True)


def owned_host_copy(leaf):
    """Device array -> host numpy array that OWNS its memory (single-leaf
    form; batch multi-leaf gathers with ONE ``jax.device_get`` of the
    whole tree, then :func:`ensure_owned` per array)."""
    return ensure_owned(jax.device_get(leaf))


def encode_array(arr):
    """numpy array -> (npz-safe array, recorded dtype name or None)."""
    arr = np.asarray(arr)
    if arr.dtype.name in _NPZ_NATIVE:
        return arr, None
    view = _WIDTH_VIEW.get(arr.dtype.itemsize)
    if view is None:
        raise TypeError(f"cannot serialize dtype {arr.dtype} "
                        f"(itemsize {arr.dtype.itemsize})")
    return arr.view(view), arr.dtype.name


def decode_array(arr, dtype_name):
    if dtype_name is None:
        return arr
    return arr.view(np.dtype(dtype_name))


class CheckpointSnapshot:
    """Immutable host copy of everything one checkpoint contains."""

    __slots__ = ("tag", "model_states", "model_dtypes", "optim_states",
                 "meta", "client_state_pkl", "save_latest")

    def __init__(self, tag, model_states, model_dtypes, optim_states, meta,
                 client_state_pkl=None, save_latest=True):
        self.tag = str(tag)
        self.model_states = model_states
        self.model_dtypes = model_dtypes
        self.optim_states = optim_states
        self.meta = meta
        self.client_state_pkl = client_state_pkl
        self.save_latest = bool(save_latest)

    @property
    def global_steps(self):
        return int(self.meta.get("global_steps", -1))

    def nbytes(self):
        return sum(int(a.nbytes) for a in self.model_states.values()) + sum(
            int(a.nbytes) for a in self.optim_states.values())

    def file_writers(self):
        """Ordered {filename: fn(file_object)} for the atomic writer."""
        writers = {
            MODEL_STATES_NPZ:
                lambda f: np.savez(f, **self.model_states),
            OPTIM_STATES_NPZ:
                lambda f: np.savez(f, **self.optim_states),
            META_JSON:
                lambda f: f.write(json.dumps(self.meta, indent=2).encode()),
        }
        if self.client_state_pkl is not None:
            writers[CLIENT_STATE_PKL] = (
                lambda f: f.write(self.client_state_pkl))
        return writers

    def manifest_extra(self):
        return {"global_steps": self.global_steps,
                "model_dtypes": self.model_dtypes}


def capture_engine_snapshot(engine, tag, client_state=None, save_latest=True):
    """Gather engine state to host and freeze it as a snapshot.

    Layout mirrors the reference's (SURVEY §3.5): a model-states archive,
    a ZeRO optimizer-states archive (flat master saved *unpadded* so a
    different DP degree can re-pad on load — the reference's elastic
    checkpoint trick, ``stage1.py:848-883``), and a meta json.
    """
    model_states, model_dtypes = {}, {}
    for key, arr in engine._params_to_host(engine.get_params()).items():
        enc, dtype_name = encode_array(arr)
        model_states[key] = enc
        if dtype_name is not None:
            model_dtypes[key] = dtype_name

    unpadded = engine.flat.gather_master_unpadded(engine.state["master"])
    # flat-shaped optimizer-state leaves are saved unpadded too, so the
    # whole optimizer checkpoint is DP-degree elastic.  Row-group tuples
    # (grouped offload state) are treated as one logical leaf so the saved
    # format stays identical to the ungrouped layout — checkpoints stay
    # portable across offload modes and DP degrees.
    optim_states = {"master": np.asarray(unpadded)}
    flat_opt, _ = jax.tree_util.tree_flatten_with_path(
        engine.state["opt"], is_leaf=lambda x: type(x) is tuple)
    small = {}
    for path, leaf in flat_opt:
        key = tree_path_key(path)
        if type(leaf) is tuple or leaf.shape == engine.flat.flat_shape:
            optim_states[f"opt/{key}"] = engine.flat.gather_master_unpadded(
                leaf)
        else:
            small[f"opt/{key}"] = leaf
    if small:
        # non-flat leaves (step counters, per-rank scalars): ONE batched
        # transfer instead of one blocking round-trip per leaf
        optim_states.update({k: ensure_owned(v)
                             for k, v in jax.device_get(small).items()})

    # reduced-precision offload state: error-feedback residual buffers
    # are training state — carried under qres/<name> in the same
    # unpadded fp32 checkpoint format (upcast is exact), so a same-
    # layout resume is bit-identical and a cross-dtype load can fold
    # them back into the values (engine.load_checkpoint)
    qres = engine.state.get("qres") if hasattr(engine, "state") else None
    state_dtype_meta = None
    if qres:
        for name, buf in qres.items():
            optim_states[f"qres/{name}"] = engine.flat.gather_master_unpadded(
                buf)
    if getattr(engine, "_state_reduced", False):
        state_dtype_meta = dict(
            engine._config.zero_config.offload_state_dtype)

    scale = engine.state["scale"]
    # ONE transfer for every device scalar in the meta block: each
    # separate device_get is its own blocking wire round-trip, and this
    # gather runs with train_batch stalled behind it (dslint DSH203)
    scalars = jax.device_get({
        "skipped": engine.state["skipped"], "ustep": engine.state["ustep"],
        "cur_scale": scale.cur_scale, "cur_iter": scale.cur_iter,
        "last_overflow_iter": scale.last_overflow_iter,
        "cur_hysteresis": scale.cur_hysteresis})
    meta = {
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "global_samples": engine.global_samples,
        "skipped_steps": int(scalars["skipped"]),
        "scale_state": {
            "cur_scale": float(scalars["cur_scale"]),
            "cur_iter": int(scalars["cur_iter"]),
            "last_overflow_iter": int(scalars["last_overflow_iter"]),
            "cur_hysteresis": int(scalars["cur_hysteresis"]),
        },
        "ustep": int(scalars["ustep"]),
        "lr_scheduler": (engine.lr_scheduler.state_dict()
                         if engine.lr_scheduler is not None else None),
        "dp_world_size": engine.dp_world_size,
        "mp_world_size": engine.mp_world_size,
        "zero_stage": engine.zero_stage,
        "param_count": int(sum(engine.segments.sizes)),
        "model_dtypes": model_dtypes,
    }
    # dataloader/sampler cursor: a resumed run — possibly at a DIFFERENT
    # dp degree on the elastic schedule — must consume the exact next
    # global batches (no replay, no skip).  Saves happen at optimizer-
    # step boundaries, so the position is a multiple of the fixed global
    # batch and re-factors over any valid micro x dp geometry.
    loader = getattr(engine, "training_dataloader", None)
    if loader is not None and hasattr(loader, "state_dict"):
        meta["data_state"] = loader.state_dict()
    if state_dtype_meta is not None:
        # which storage layout wrote this checkpoint: loads into the
        # SAME layout restore raw buffers bit-exactly; any other layout
        # folds residuals and re-rounds once
        meta["offload_state_dtype"] = state_dtype_meta

    client_state_pkl = (pickle.dumps(client_state)
                        if client_state else None)
    return CheckpointSnapshot(tag, model_states, model_dtypes, optim_states,
                              meta, client_state_pkl, save_latest)


def load_model_states(ckpt_dir):
    """Read ``model_states.npz`` back in its true dtypes.

    Pre-manifest checkpoints saved everything as fp32 and carry no dtype
    map — their arrays pass through unchanged, so old checkpoints load
    transparently into runs of any compute dtype.
    """
    import os

    meta_path = os.path.join(str(ckpt_dir), META_JSON)
    dtype_map = {}
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            dtype_map = json.load(f).get("model_dtypes") or {}
    with np.load(os.path.join(str(ckpt_dir), MODEL_STATES_NPZ)) as npz:
        return {k: decode_array(npz[k], dtype_map.get(k))
                for k in npz.files}
