from .logging import logger, log_dist
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .distributed import init_distributed, get_rank, get_world_size
