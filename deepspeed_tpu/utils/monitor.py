"""Training metrics monitor (TensorBoard + JSONL).

Analog of the reference engine's inline tensorboard logging
(``deepspeed/runtime/engine.py:149-150, 1014-1067``): scalar summaries of
loss / learning rate / loss scale / throughput per optimizer step, gated on
the ``tensorboard`` config section.  A JSONL event log is always written
alongside (cheap, grep-able, no reader dependency); the TensorBoard writer
is used when ``torch.utils.tensorboard`` is importable.

Since the telemetry subsystem (``deepspeed_tpu/telemetry``) landed, this
monitor is a thin *consumer* of the per-step scalar flow: the engine
routes print-cadence scalars through
:meth:`~deepspeed_tpu.telemetry.manager.TelemetryManager.step_metrics`,
which feeds the structured event stream / metrics registry AND this
writer — the TB/JSONL output and its config gating are unchanged, and
the canonical queryable record is the telemetry event stream.
"""

import json
import os
import time

from .logging import logger


class TrainingMonitor:
    """Writes per-step scalars; rank-0 only (reference gates on
    ``global_rank == 0``, ``engine.py:1014``)."""

    def __init__(self, enabled, output_path="", job_name="DeepSpeedJobName",
                 rank=0):
        self.enabled = bool(enabled) and rank == 0
        self._tb = None
        self._jsonl = None
        if not self.enabled:
            return
        base = os.path.join(output_path or "runs", job_name)
        os.makedirs(base, exist_ok=True)
        self._jsonl_path = os.path.join(base, "events.jsonl")
        self._jsonl = open(self._jsonl_path, "a")
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=base)
        except Exception as e:  # tensorboard optional
            logger.warning(f"tensorboard writer unavailable ({e}); "
                           f"scalars go to {self._jsonl_path} only")

    def write_scalars(self, step, scalars):
        """``scalars``: {tag: float}."""
        if not self.enabled:
            return
        rec = {"step": int(step), "time": time.time()}
        rec.update({k: float(v) for k, v in scalars.items()})
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        if self._tb is not None:
            for tag, val in scalars.items():
                self._tb.add_scalar(tag, float(val), int(step))
            # writes happen on the (coarse) steps_per_print cadence, so
            # flush eagerly — a run exiting before SummaryWriter's timed
            # flush would otherwise lose its tail scalars
            self._tb.flush()

    def flush(self):
        if self._tb is not None:
            self._tb.flush()

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
