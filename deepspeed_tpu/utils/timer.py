"""Wall-clock + throughput timers.

TPU analog of the reference's ``deepspeed/utils/timer.py``:
- ``SynchronizedWallClockTimer`` (reference ``:19-94``) — named timers whose
  start/stop fence outstanding device work.  The reference calls
  ``torch.cuda.synchronize()``; here the fence is draining the async XLA
  dispatch queue (``jax.block_until_ready`` has to be applied by callers on
  their live arrays; as a global fence we submit and block on a trivial
  computation, which orders after previously enqueued work on that device).
- ``ThroughputTimer`` (reference ``:97-163``) — samples/sec with warmup skip.
"""

import time

from .logging import log_dist, logger


def device_fence():
    """Block until previously dispatched device computations complete.

    A host round-trip (``device_get`` of a freshly dispatched computation)
    rather than ``block_until_ready``: on remote-attached platforms the
    latter has been observed to return before remote execution finishes,
    while a fetched result cannot exist until everything queued before it
    (per-device dispatch is in order) has run.
    """
    try:
        import jax
        import jax.numpy as jnp

        jax.device_get(jnp.zeros(()) + 0)
    except Exception:  # dslint: disable=DSE502 -- best-effort fence; timers still run without a backend
        pass


class SynchronizedWallClockTimer:
    """Named timers with device fencing, matching the reference API."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.time()

        def start(self, sync=True):
            assert not self.started_, f"{self.name_} timer has already been started"
            if sync:
                device_fence()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, sync=True):
            assert self.started_, "timer is not started"
            if sync:
                device_fence()
            self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

        def mean(self, count):
            return self.elapsed(reset=False) / max(count, 1)

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        """Aggregate allocation stats over ALL local devices (summing —
        on a multi-chip host, device 0 alone understates the footprint by
        the local device count).  Shared implementation:
        ``profiling.memory.device_memory_summary``."""
        try:
            from ..profiling.memory import (device_memory_summary,
                                            format_memory_summary)

            return format_memory_summary(device_memory_summary())
        except Exception:
            return "mem stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        """Log named timers; ``ranks`` filters to those process indices
        (None = all, matching ``log_dist``) and ``memory_breakdown``
        appends the cross-device memory summary — both kwargs existed in
        the reference signature and were silently ignored here."""
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks)


class ThroughputTimer:
    """Samples/sec with warm-up skipping (reference ``timer.py:97-163``)."""

    def __init__(self, batch_size, num_workers, start_step=2, steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.counted_steps = 0
        self._window_anchor = None
        self._window_anchor_step = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            if self._window_anchor is None:
                # first measured window opens here, fenced so queued warmup
                # work is not billed to it
                device_fence()
                self._window_anchor = time.time()
                self._window_anchor_step = self.global_step_count
            self.start_time = time.time()

    def stop(self, report_speed=True):
        """Fencing is a host round-trip, so it happens only on reporting
        steps; durations are measured over whole fenced *windows* (time
        between consecutive fenced stops ÷ steps in between) — unfenced
        per-step times would only measure async dispatch."""
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        self.global_step_count += 1
        if self.start_time > 0:
            if (self.global_step_count % self.steps_per_output == 0
                    and self._window_anchor is not None):
                device_fence()
                now = time.time()
                window_steps = self.global_step_count - self._window_anchor_step
                window_time = now - self._window_anchor
                self.total_elapsed_time += window_time
                self.counted_steps += window_steps
                self._window_anchor = now
                self._window_anchor_step = self.global_step_count
                if report_speed and window_steps > 0 and window_time > 0:
                    avg = self.avg_samples_per_sec()
                    # before any counted window the running average is 0.0
                    # (not -inf); printing "RunningAvgSamplesPerSec=0.00"
                    # would be as misleading, so the field is omitted
                    avg_part = (f"RunningAvgSamplesPerSec={avg:.2f}, "
                                if avg > 0 else "")
                    self.logging(
                        f"{self.__class__.__name__}: epoch={self.epoch_count}/"
                        f"micro_step={self.micro_step_count}/"
                        f"global_step={self.global_step_count}, "
                        f"{avg_part}"
                        f"CurrSamplesPerSec={self.batch_size * self.num_workers * window_steps / window_time:.2f}"
                    )

    def avg_samples_per_sec(self):
        if self.counted_steps > 0 and self.total_elapsed_time > 0:
            samples_per_step = self.batch_size * self.num_workers
            avg_time_per_step = self.total_elapsed_time / self.counted_steps
            return samples_per_step / avg_time_per_step
        # no counted window yet: 0.0, not the reference's float("-inf") —
        # callers format this into logs and "-inf samples/sec" is noise
        return 0.0
