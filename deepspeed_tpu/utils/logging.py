"""Logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py:7-56``
(single framework logger + rank-filtered ``log_dist``).  On TPU the "rank"
is ``jax.process_index()`` (one process per host under multi-host SPMD),
not a per-device rank.
"""

import logging
import sys
from typing import Iterable, Optional

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"


class LoggerFactory:
    @staticmethod
    def create_logger(name: str = "DeepSpeedTPU", level=logging.INFO) -> logging.Logger:
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(_FORMAT)
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger()


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level=logging.INFO) -> None:
    """Log ``message`` only on the listed process indices (``[-1]`` or None = all).

    Mirrors the rank-filtering semantics of the reference ``log_dist``
    (``deepspeed/utils/logging.py:40-56``) with JAX process indices standing
    in for torch.distributed ranks.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else []
    should_log = not ranks or -1 in ranks or my_rank in ranks
    if should_log:
        logger.log(level, f"[Rank {my_rank}] {message}")
