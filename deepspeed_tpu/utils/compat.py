"""Cross-version jax API shims.

The codebase targets the modern public ``jax.shard_map`` signature
(``axis_names=...``, ``check_vma=...``).  On older jax (<= 0.4.x) that
API lives at ``jax.experimental.shard_map.shard_map`` with the manual
axes expressed inversely (``auto`` = the non-manual complement) and
``check_vma`` spelled ``check_rep``; this wrapper translates.

Partial-manual mode (a non-empty ``auto`` set) is unusable on the 0.4.x
line: XLA's SPMD partitioner hard-aborts with an ``IsManualSubgroup``
CHECK as soon as the region contains a ``ppermute`` and any auto axis
has size > 1.  The shim therefore takes EVERY mesh axis manual on old
jax — specs keep their meaning (``P()`` = replicated), so results are
unchanged; operands sharded over would-be-auto axes are gathered at the
region boundary instead of staying GSPMD-partitioned inside (a
perf-only cost, and only on jax versions that lack the public API).

Two caveats callers must respect on old jax, enforced at the two
affected call sites:

- differentiating THROUGH a shard_map whose backward needs a scalar
  residual trips a transpose bug (mis-named residual -> ``_SpecError``,
  or silently wrong values): the pipeline engine keeps its loss carry
  1-D (runtime/pipe/engine.py), and ring attention — whose softmax
  residuals cannot be controlled from outside — skips shard_map
  entirely and computes the mathematically identical dense attention
  under GSPMD (``PARTIAL_MANUAL_SHARD_MAP`` below).
- regions that differentiate internally (engine sparse-grad step,
  onebit/cpu-adam updates) are unaffected: nothing crosses the
  boundary under AD.
"""

import jax

if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        # the pre-axis_size idiom: psum of a literal constant-folds to a
        # static python int at trace time
        return jax.lax.psum(1, axis_name)


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=True):
        # axis_names accepted for signature parity; every axis goes manual
        # (partial-manual mode aborts XLA on 0.4.x — see module docstring)
        del axis_names
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs,
                              check_rep=bool(check_vma))


# True where jax supports manual collectives over a subset of mesh axes
# with the rest left to GSPMD.  Ring attention requires that combination
# when differentiated (see module docstring) and falls back to the dense
# computation without it.
PARTIAL_MANUAL_SHARD_MAP = hasattr(jax, "shard_map")
