"""Multi-host bootstrap.

TPU analog of ``deepspeed/utils/distributed.py:12-142`` in the reference.
The reference wires up ``torch.distributed.init_process_group('nccl')`` from a
MASTER_ADDR/RANK env dance (optionally discovered through mpi4py).  On TPU the
runtime already knows the pod topology; ``jax.distributed.initialize()`` only
needs a coordinator address and the process count, and single-host runs need
no initialization at all.
"""

import os

from .logging import logger

_initialized = False

# MPI-scheduled launches (--launcher=openmpi|mvapich) skip the per-node
# spawner: mpirun starts each rank directly, so rank/world-size come from
# the MPI library's environment — the analog of the reference's mpi4py
# discovery (``distributed.py:12-142``).  Ordered by specificity.
_MPI_RANK_VARS = ("OMPI_COMM_WORLD_RANK", "MV2_COMM_WORLD_RANK", "PMI_RANK")
_MPI_SIZE_VARS = ("OMPI_COMM_WORLD_SIZE", "MV2_COMM_WORLD_SIZE", "PMI_SIZE")


def _first_env(names):
    for n in names:
        if n in os.environ:
            return int(os.environ[n])
    return None


def _resolve_env(mpi=True):
    """(coordinator, num_processes, process_id) from the launcher's DS_*
    contract, falling back to MPI env for mpirun-scheduled ranks."""
    coordinator = os.environ.get("DS_COORDINATOR")
    num = int(os.environ.get("DS_NUM_PROCESSES", "0") or 0)
    pid = (int(os.environ["DS_PROCESS_ID"])
           if "DS_PROCESS_ID" in os.environ else None)
    if mpi:
        if not num:
            num = _first_env(_MPI_SIZE_VARS) or 0
        if pid is None:
            pid = _first_env(_MPI_RANK_VARS)
    return coordinator, num, pid


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     coordinator_address: str = None,
                     num_processes: int = None,
                     process_id: int = None,
                     verbose: bool = True):
    """Initialize multi-host JAX if the environment asks for it.

    Env contract (set by our launcher, mirrors the reference launcher's
    MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK): ``DS_COORDINATOR``,
    ``DS_NUM_PROCESSES``, ``DS_PROCESS_ID``.  No-op on single host.
    """
    global _initialized
    if _initialized:
        return
    import jax

    env_c, env_n, env_p = _resolve_env(mpi=auto_mpi_discovery)
    coordinator_address = coordinator_address or env_c
    num_processes = num_processes or env_n
    process_id = process_id if process_id is not None else env_p

    if coordinator_address and num_processes > 1:
        if verbose:
            logger.info(
                f"Initializing multi-host JAX: coordinator={coordinator_address} "
                f"num_processes={num_processes} process_id={process_id}")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def get_rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1
