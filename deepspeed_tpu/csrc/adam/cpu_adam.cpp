// Host-resident Adam(W) kernel — the native component analog of the
// reference's csrc/adam/cpu_adam.cpp:21-682 (AVX512/AVX2 tiled, OpenMP).
// Vectorization is delegated to the compiler (-O3 -march=native auto-
// vectorizes the fused loop; the reference hand-writes SIMD_* intrinsics
// for the same arithmetic), parallelism to OpenMP like the reference's
// parallel_for. Exposed with a C ABI for ctypes; invoked from inside
// jitted programs via jax.pure_callback (ops/adam/cpu_adam.py).

#include <cmath>
#include <cstdint>

extern "C" void ds_adam_step(
    float* p_out, float* m_out, float* v_out,
    const float* p, const float* m, const float* v, const float* g,
    long long n, float lr, float beta1, float beta2, float eps,
    float weight_decay, float bc1, float bc2, int adamw) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    float gi = g[i];
    float pi = p[i];
    if (!adamw) gi += weight_decay * pi;  // L2 mode: decay folded into grad
    float mi = beta1 * m[i] + (1.0f - beta1) * gi;
    float vi = beta2 * v[i] + (1.0f - beta2) * gi * gi;
    float denom = sqrtf(vi / bc2) + eps;
    float upd = (mi / bc1) / denom;
    if (adamw) upd += weight_decay * pi;  // AdamW: decoupled decay
    p_out[i] = pi - lr * upd;
    m_out[i] = mi;
    v_out[i] = vi;
  }
}
