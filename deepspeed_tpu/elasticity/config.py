"""Elasticity config object (reference ``deepspeed/elasticity/config.py``)."""

import json

from . import constants as EC


class ElasticityError(Exception):
    """Base exception for all elasticity related errors."""


class ElasticityConfigError(ElasticityError):
    """Elasticity configuration error."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size incompatible with the elastic config's valid device counts."""


class ElasticityConfig:
    """Typed view of the ``"elasticity"`` subsection.

    Required when enabled: ``max_train_batch_size`` and ``micro_batch_sizes``
    (reference ``config.py:48-60``).  "gpus" in key names is kept for config
    compatibility; on TPU the unit is chips (data-parallel mesh slots).
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(EC.ENABLED, EC.ENABLED_DEFAULT)
        if self.enabled:
            for required in (EC.MAX_ACCEPTABLE_BATCH_SIZE, EC.MICRO_BATCHES):
                if required not in param_dict:
                    raise ElasticityConfigError(f"Elasticity config missing {required}")
            self.max_acceptable_batch_size = param_dict[EC.MAX_ACCEPTABLE_BATCH_SIZE]
            self.micro_batches = param_dict[EC.MICRO_BATCHES]
        else:
            self.max_acceptable_batch_size = param_dict.get(
                EC.MAX_ACCEPTABLE_BATCH_SIZE, EC.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
            self.micro_batches = param_dict.get(EC.MICRO_BATCHES, EC.MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"Elasticity expected {EC.MICRO_BATCHES} to be a list, got "
                f"{type(self.micro_batches)}: {self.micro_batches}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"Elasticity expected {EC.MICRO_BATCHES} to contain positive ints, "
                f"got {self.micro_batches}")

        self.min_gpus = param_dict.get(EC.MIN_GPUS, EC.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(EC.MAX_GPUS, EC.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError(
                f"Elasticity min/max device counts must be > 0, got min={self.min_gpus} "
                f"max={self.max_gpus}")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"Elasticity min_gpus cannot exceed max_gpus: min={self.min_gpus} "
                f"max={self.max_gpus}")

        self.min_time = param_dict.get(EC.MIN_TIME, EC.MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(f"Elasticity min_time must be >= 0: {self.min_time}")

        self.version = param_dict.get(EC.VERSION, EC.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(EC.PREFER_LARGER_BATCH,
                                                       EC.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            EC.IGNORE_NON_ELASTIC_BATCH_INFO, EC.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
