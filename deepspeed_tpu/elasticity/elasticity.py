"""Elastic batch-size planning.

Behavioral port of ``deepspeed/elasticity/elasticity.py`` (reference
``:122-171`` for the v0.1 algorithm, ``:240-334`` for the API): given
acceptable micro-batch sizes and a max global batch, choose the global batch
size divisible by the largest number of device counts, so the scheduler can
scale the job across that set without changing convergence (global batch
fixed; micro×grad_acc×devices re-factored per world size).

Elasticity here is *ahead-of-time planning*, exactly as in the reference —
not live scaling (SURVEY §5.3).
"""

import json
import math
import os

from ..utils.logging import logger
from . import constants as EC
from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize)

# Highly composite numbers: candidates with many divisors ⇒ many compatible
# device counts.  Same table as reference ``elasticity.py:19-58``.
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280, 720720,
]


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """For each base, the largest base×HCN not exceeding the cap."""
    candidates = set()
    for base in base_list:
        best = base
        for hcn in HCN_LIST:
            if base * hcn > max_acceptable_batch_size:
                break
            best = base * hcn
        candidates.add(best)
    return list(candidates)


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """Device counts n such that batch_size = n × micro × k for some micro in
    ``micro_batches`` and integer k (reference ``elasticity.py:78-94``)."""
    valid = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_devs = batch_size // micro_batch
        divisors = [max_devs] + [i for i in range(1, max_devs // 2 + 1) if max_devs % i == 0]
        for n in divisors:
            if min_valid_gpus <= n <= max_valid_gpus:
                valid.add(n)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus, prefer_larger):
    max_valid = 0
    best_valid_gpus = None
    best_batch = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        cur = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        better_count = len(cur) > max_valid
        tie_break = len(cur) == max_valid and (
            (prefer_larger and batch_size > best_batch)
            or (not prefer_larger and batch_size < best_batch))
        if better_count or tie_break:
            max_valid = len(cur)
            best_valid_gpus = cur
            best_batch = batch_size
    return best_batch, best_valid_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size, min_gpus=None,
                             max_gpus=None, prefer_larger=True):
    """v0.1 heuristic (reference ``elasticity.py:122-171``): candidate bases
    are each micro-batch and their LCM, each scaled to the largest HCN
    multiple under the cap; pick the candidate with the most compatible
    device counts."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    assert all(mb <= max_acceptable_batch_size for mb in micro_batches), (
        f"All micro batches must be <= max_acceptable_batch_size={max_acceptable_batch_size}")

    lcm = micro_batches[0]
    for mb in micro_batches[1:]:
        lcm = lcm * mb // math.gcd(lcm, mb)

    candidates = get_candidate_batch_sizes(list(micro_batches) + [lcm],
                                           max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def elasticity_enabled(ds_config: dict):
    if EC.ELASTICITY not in ds_config:
        return False
    return ds_config[EC.ELASTICITY].get(EC.ENABLED, EC.ENABLED_DEFAULT)


def parse_version(version) -> tuple:
    """``"0.3.11"`` / ``0.1`` / ``"0"`` -> a comparable numeric tuple,
    zero-padded to three components so ``"0" == "0.0.0"`` (this repo's
    versions are plain dotted numerics; anything else raises)."""
    parts = str(version).strip().split(".")
    try:
        nums = tuple(int(p) for p in parts)
    except ValueError as e:
        raise ElasticityConfigError(
            f"cannot parse version {version!r} as a dotted numeric") from e
    return nums + (0,) * (3 - len(nums)) if len(nums) < 3 else nums


def _normalize_field(field, value):
    """Canonical form of one immutability-checked field, so a respawned
    process comparing its runtime config against the
    ``DEEPSPEED_ELASTICITY_CONFIG`` json the launcher exported never
    rejects a SAME-config resume over representation drift: version
    compares as a zero-padded numeric tuple (``0.1`` vs ``"0.1"`` vs
    ``"0.1.0"``), micro-batch lists as sorted int tuples (json
    round-trips tuples into lists)."""
    if field == "version":
        return parse_version(value)
    if field == "micro_batches":
        return tuple(sorted(int(m) for m in value))
    return value


def ensure_immutable_elastic_config(runtime_elastic_config_dict: dict):
    """Fail if the resource scheduler planned with a different elastic config
    than the runtime sees (reference ``elasticity.py:206-237``); the plan is
    carried in the ``DEEPSPEED_ELASTICITY_CONFIG`` env var.

    Comparisons are value-based, not representation-based: a launcher
    respawn re-exports the same config through json, and ``0.1 != "0.1"``
    must not kill an elastic resume (the resize-on-failure loop re-enters
    here on every respawn)."""
    if EC.DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        scheduler_config = ElasticityConfig(
            json.loads(os.environ[EC.DEEPSPEED_ELASTICITY_CONFIG]))
        runtime_config = ElasticityConfig(runtime_elastic_config_dict)
        for field in ("max_acceptable_batch_size", "micro_batches", "version"):
            sched_val = _normalize_field(field,
                                         getattr(scheduler_config, field))
            run_val = _normalize_field(field, getattr(runtime_config, field))
            if sched_val != run_val:
                raise ElasticityConfigError(
                    f"Elastic config {field}={sched_val} seen by resource scheduler does "
                    f"not match config passed to runtime {field}={run_val}")
    else:
        logger.warning(
            "Unable to find DEEPSPEED_ELASTICITY_CONFIG environment variable, cannot "
            "guarantee resource scheduler will scale this job using compatible device counts.")


def compute_elastic_config(ds_config: dict, target_deepspeed_version=None,
                           world_size=0):
    """Compute (final_batch_size, valid_device_counts[, micro_batch]) for an
    elastic job (reference ``elasticity.py:240-334``).

    ``target_deepspeed_version`` defaults to this package's own version;
    passing one checks it against :data:`EC.MINIMUM_DEEPSPEED_VERSION`
    under THIS repo's versioning (plain dotted numerics, zero-padded, so
    the historical ``"0"`` sentinel still means ``0.0.0``, not a parse
    error — the reference compared version strings lexically)."""
    if not isinstance(ds_config, dict):
        raise ValueError(
            f"Expected ds_config dict, got {type(ds_config)}: {ds_config}")
    if target_deepspeed_version is None:
        from .. import __version__ as target_deepspeed_version
    if (parse_version(target_deepspeed_version)
            < parse_version(EC.MINIMUM_DEEPSPEED_VERSION)):
        raise ElasticityConfigError(
            f"target deepspeed version {target_deepspeed_version} is older "
            f"than the minimum elasticity-capable version "
            f"{EC.MINIMUM_DEEPSPEED_VERSION}")
    if EC.ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            f"'{EC.ELASTICITY}' is missing from config json, please add it if "
            "running an elastic training job.")
    elastic_config_dict = ds_config[EC.ELASTICITY]
    if not elastic_config_dict.get(EC.ENABLED, EC.ENABLED_DEFAULT):
        raise ElasticityConfigError(
            "Elasticity is disabled, please enable it ('enabled':true) if "
            "running an elastic training job.")

    elastic_config = ElasticityConfig(elastic_config_dict)
    # algorithm-version comparisons go through parse_version too, so
    # "0.1.0" means v0.1 instead of crashing float()
    if (parse_version(elastic_config.version)
            > parse_version(EC.LATEST_ELASTICITY_VERSION)):
        raise ElasticityConfigError(
            f"Attempting to run elasticity version {elastic_config.version} but "
            f"runtime only supports up to {EC.LATEST_ELASTICITY_VERSION}")

    if parse_version(elastic_config.version) == parse_version("0.1"):
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(
            f"Unable to find elastic logic for version: {elastic_config.version}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not valid with the current list of "
                f"valid device counts: {valid_gpus}")
        # Pick the largest micro batch that evenly divides this world's share.
        micro_batch_size = None
        for mbsz in sorted(set(elastic_config.micro_batches), reverse=True):
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        assert micro_batch_size is not None, (
            f"Unable to find divisible micro batch size: world_size={world_size}, "
            f"final_batch_size={final_batch_size}, micro_batches="
            f"{elastic_config.micro_batches}.")
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus
