from .elasticity import (compute_elastic_config, elasticity_enabled,
                         ensure_immutable_elastic_config)
from .config import (ElasticityConfig, ElasticityConfigError, ElasticityError,
                     ElasticityIncompatibleWorldSize)
from .supervisor import (DS_ELASTIC_TARGET_WORLD_SIZE, ElasticPlan,
                         elastic_world_size, export_plan_env,
                         normalized_elastic_config, plan_world_size)
