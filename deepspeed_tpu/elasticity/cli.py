"""Elastic-config inspector CLI (reference ``bin/ds_elastic``): show the
final batch size, valid accelerator counts, and micro-batch plan an
elastic config resolves to.  Installed as the ``ds_elastic`` console
script (see ``pyproject.toml``)."""
import argparse
import json

from deepspeed_tpu.elasticity import compute_elastic_config


def main():
    parser = argparse.ArgumentParser(description="DeepSpeed-TPU elasticity")
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="DeepSpeed config json with an elasticity block")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="resolve for this accelerator count")
    args = parser.parse_args()
    with open(args.config) as f:
        ds_config = json.load(f)
    res = compute_elastic_config(ds_config, target_deepspeed_version="0.3.11",
                                 world_size=args.world_size)
    if args.world_size:
        final_batch, valid_gpus, micro_batch = res
        print(f"final global batch:   {final_batch}")
        print(f"valid chip counts:    {valid_gpus}")
        print(f"micro batch @ w={args.world_size}: {micro_batch}")
    else:
        final_batch, valid_gpus = res
        print(f"final global batch:   {final_batch}")
        print(f"valid chip counts:    {valid_gpus}")


if __name__ == "__main__":
    main()
