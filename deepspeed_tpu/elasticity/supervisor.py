"""Elastic fleet supervisor: the re-planning half of resize-on-failure.

The HCN planner (:func:`~deepspeed_tpu.elasticity.compute_elastic_config`)
is ahead-of-time: it fixes ONE global batch size and the set of device
counts that batch can re-factor over without changing convergence.  This
module turns that plan into the launcher's runtime decision: given the
devices still alive after a failure or preemption notice, pick the
largest valid world size that fits, re-derive micro-batch x grad-accum
so the global batch stays on the pre-declared schedule, and hand the
launcher the env contract its respawned children resume under.

Env contract (consumed by training scripts and ``DeepSpeedConfig``):

- ``DS_ELASTIC_TARGET_WORLD_SIZE`` — the data-parallel world size the
  supervisor planned for this (re)spawn; scripts size their mesh from it
  (:func:`elastic_world_size`).
- ``DEEPSPEED_ELASTICITY_CONFIG`` — the normalized elastic config json,
  so ``ensure_immutable_elastic_config`` proves every respawn still
  trains on the same schedule (a drifted config fails loudly instead of
  silently changing convergence).

Jax-free on purpose: the launcher imports this next to its other
stdlib-only collaborators.
"""

import json
import os
from collections import namedtuple

from ..utils.logging import logger
from . import constants as EC
from .config import ElasticityIncompatibleWorldSize
from .elasticity import compute_elastic_config

#: env var carrying the supervisor's planned data-parallel world size
DS_ELASTIC_TARGET_WORLD_SIZE = "DS_ELASTIC_TARGET_WORLD_SIZE"

ElasticPlan = namedtuple(
    "ElasticPlan",
    ["world_size",        # planned data-parallel device count
     "micro_batch",       # per-device micro batch at that world size
     "grad_accum",        # accumulation steps keeping the global batch
     "global_batch",      # the schedule's fixed global batch size
     "valid_world_sizes"  # every device count the schedule admits
     ])


def elastic_world_size(default=None):
    """The supervisor-planned world size for THIS process (or
    ``default`` when launched outside an elastic supervisor)."""
    val = os.environ.get(DS_ELASTIC_TARGET_WORLD_SIZE, "")
    return int(val) if val else default


def normalized_elastic_config(elastic_config_dict: dict) -> dict:
    """Canonical, json-stable form of an ``elasticity`` config block —
    what the supervisor exports as ``DEEPSPEED_ELASTICITY_CONFIG``.
    Micro-batch lists sort into one representation; the version rides
    through untouched (the immutability check compares versions as
    parsed numeric tuples, so ``0.1`` / ``"0.1"`` / ``"0.1.0"`` already
    agree without lossy coercion here)."""
    out = dict(elastic_config_dict)
    if EC.MICRO_BATCHES in out:
        out[EC.MICRO_BATCHES] = sorted(int(m) for m in out[EC.MICRO_BATCHES])
    return out


def plan_world_size(elastic_config_dict: dict, device_budget: int,
                    target_deepspeed_version=None) -> ElasticPlan:
    """Largest planner-valid world size not exceeding ``device_budget``,
    with the micro-batch x grad-accum factorization that keeps the
    global batch on the elastic schedule.

    Raises :class:`ElasticityIncompatibleWorldSize` when no valid device
    count fits the budget (fleet shrunk below the schedule's floor) —
    the launcher treats that as a terminal, non-respawnable condition.
    """
    ds_config = {EC.ELASTICITY: dict(elastic_config_dict)}
    final_batch, valid = compute_elastic_config(
        ds_config, target_deepspeed_version=target_deepspeed_version)
    fits = [w for w in valid if w <= int(device_budget)]
    if not fits:
        raise ElasticityIncompatibleWorldSize(
            f"no valid elastic world size fits {device_budget} surviving "
            f"device(s); the schedule admits {valid}")
    world = max(fits)
    _, _, micro = compute_elastic_config(
        ds_config, target_deepspeed_version=target_deepspeed_version,
        world_size=world)
    accum = final_batch // (micro * world)
    plan = ElasticPlan(world_size=world, micro_batch=micro,
                       grad_accum=accum, global_batch=final_batch,
                       valid_world_sizes=tuple(valid))
    logger.info(
        "elastic plan: %d surviving device(s) -> world_size=%d "
        "(micro=%d x accum=%d x dp=%d = global %d)", device_budget,
        world, micro, accum, world, final_batch)
    return plan


def export_plan_env(env: dict, elastic_config_dict: dict,
                    plan: ElasticPlan) -> dict:
    """Write the elastic env contract for one child spawn into ``env``
    (mutated and returned): the planned world size plus the normalized
    schedule for the immutability check on resume."""
    env[DS_ELASTIC_TARGET_WORLD_SIZE] = str(plan.world_size)
    env[EC.DEEPSPEED_ELASTICITY_CONFIG] = json.dumps(
        normalized_elastic_config(elastic_config_dict), sort_keys=True)
    return env
