"""Elastic fleet supervisor: the re-planning half of resize-on-failure.

The HCN planner (:func:`~deepspeed_tpu.elasticity.compute_elastic_config`)
is ahead-of-time: it fixes ONE global batch size and the set of device
counts that batch can re-factor over without changing convergence.  This
module turns that plan into the launcher's runtime decision: given the
devices still alive after a failure or preemption notice, pick the
largest valid world size that fits, re-derive micro-batch x grad-accum
so the global batch stays on the pre-declared schedule, and hand the
launcher the env contract its respawned children resume under.

Env contract (consumed by training scripts and ``DeepSpeedConfig``):

- ``DS_ELASTIC_TARGET_WORLD_SIZE`` — the data-parallel world size the
  supervisor planned for this (re)spawn; scripts size their mesh from it
  (:func:`elastic_world_size`).
- ``DEEPSPEED_ELASTICITY_CONFIG`` — the normalized elastic config json,
  so ``ensure_immutable_elastic_config`` proves every respawn still
  trains on the same schedule (a drifted config fails loudly instead of
  silently changing convergence).

Integrity-directed eviction (``resilience/integrity.py``): when the
fleet integrity plane names a bad rank — a state-fingerprint outlier or
a hang-quorum suspect — the resize is *aimed* instead of blind.  The
:class:`EvictionLedger` records which hostfile slots the verdicts have
indicted: their devices are charged against the elastic budget, the
slots join a blocklist every subsequent spawn respects (the suspect
host never rejoins the fleet), and evictions beyond the run's budget
escalate to the poison teardown — a fleet that keeps producing
integrity verdicts has a systemic problem no resize can fix.

Jax-free on purpose: the launcher imports this next to its other
stdlib-only collaborators.
"""

import json
import os
from collections import namedtuple

from ..utils.logging import logger
from . import constants as EC
from .config import ElasticityIncompatibleWorldSize
from .elasticity import compute_elastic_config

#: env var carrying the supervisor's planned data-parallel world size
DS_ELASTIC_TARGET_WORLD_SIZE = "DS_ELASTIC_TARGET_WORLD_SIZE"

ElasticPlan = namedtuple(
    "ElasticPlan",
    ["world_size",        # planned data-parallel device count
     "micro_batch",       # per-device micro batch at that world size
     "grad_accum",        # accumulation steps keeping the global batch
     "global_batch",      # the schedule's fixed global batch size
     "valid_world_sizes"  # every device count the schedule admits
     ])


def elastic_world_size(default=None):
    """The supervisor-planned world size for THIS process (or
    ``default`` when launched outside an elastic supervisor)."""
    val = os.environ.get(DS_ELASTIC_TARGET_WORLD_SIZE, "")
    return int(val) if val else default


def normalized_elastic_config(elastic_config_dict: dict) -> dict:
    """Canonical, json-stable form of an ``elasticity`` config block —
    what the supervisor exports as ``DEEPSPEED_ELASTICITY_CONFIG``.
    Micro-batch lists sort into one representation; the version rides
    through untouched (the immutability check compares versions as
    parsed numeric tuples, so ``0.1`` / ``"0.1"`` / ``"0.1.0"`` already
    agree without lossy coercion here)."""
    out = dict(elastic_config_dict)
    if EC.MICRO_BATCHES in out:
        out[EC.MICRO_BATCHES] = sorted(int(m) for m in out[EC.MICRO_BATCHES])
    return out


def plan_world_size(elastic_config_dict: dict, device_budget: int,
                    target_deepspeed_version=None) -> ElasticPlan:
    """Largest planner-valid world size not exceeding ``device_budget``,
    with the micro-batch x grad-accum factorization that keeps the
    global batch on the elastic schedule.

    Raises :class:`ElasticityIncompatibleWorldSize` when no valid device
    count fits the budget (fleet shrunk below the schedule's floor) —
    the launcher treats that as a terminal, non-respawnable condition.
    """
    ds_config = {EC.ELASTICITY: dict(elastic_config_dict)}
    final_batch, valid = compute_elastic_config(
        ds_config, target_deepspeed_version=target_deepspeed_version)
    fits = [w for w in valid if w <= int(device_budget)]
    if not fits:
        raise ElasticityIncompatibleWorldSize(
            f"no valid elastic world size fits {device_budget} surviving "
            f"device(s); the schedule admits {valid}")
    world = max(fits)
    _, _, micro = compute_elastic_config(
        ds_config, target_deepspeed_version=target_deepspeed_version,
        world_size=world)
    accum = final_batch // (micro * world)
    plan = ElasticPlan(world_size=world, micro_batch=micro,
                       grad_accum=accum, global_batch=final_batch,
                       valid_world_sizes=tuple(valid))
    logger.info(
        "elastic plan: %d surviving device(s) -> world_size=%d "
        "(micro=%d x accum=%d x dp=%d = global %d)", device_budget,
        world, micro, accum, world, final_batch)
    return plan


def export_plan_env(env: dict, elastic_config_dict: dict,
                    plan: ElasticPlan) -> dict:
    """Write the elastic env contract for one child spawn into ``env``
    (mutated and returned): the planned world size plus the normalized
    schedule for the immutability check on resume."""
    env[DS_ELASTIC_TARGET_WORLD_SIZE] = str(plan.world_size)
    env[EC.DEEPSPEED_ELASTICITY_CONFIG] = json.dumps(
        normalized_elastic_config(elastic_config_dict), sort_keys=True)
    return env


#: evictions one supervised run tolerates before poisoning (env
#: ``DS_INTEGRITY_MAX_EVICTIONS`` overrides): ONE bad host is the
#: cosmic-ray story the plane exists for; a fleet that keeps indicting
#: ranks after an eviction already resized around the suspect has a
#: systemic problem (bad batch of hosts, corrupted shared storage, a
#: software bug voting against itself) that shrinking cannot fix.
DEFAULT_MAX_EVICTIONS = 1


class EvictionLedger:
    """Integrity-verdict bookkeeping for one supervised run.

    The launcher records every consumed integrity verdict here:
    ``record()`` returns True while the eviction budget holds (resize
    around the suspect, blocklisting its slot) and False once the run
    must poison instead (*repeated eviction*).  ``blocked_slots`` is
    the planner-facing blocklist: every respawn spawns only from the
    slots NOT indicted by a previous verdict, so an evicted host's
    devices never rejoin the fleet no matter how many resizes follow.
    """

    def __init__(self, max_evictions=None):
        if max_evictions is None:
            raw = os.environ.get("DS_INTEGRITY_MAX_EVICTIONS",
                                 str(DEFAULT_MAX_EVICTIONS))
            try:
                max_evictions = int(raw)
            except ValueError:
                # same contract as the other env parses: a malformed
                # value degrades to the default, never kills the
                # launcher at startup
                logger.warning(
                    f"DS_INTEGRITY_MAX_EVICTIONS={raw!r} is not an "
                    f"integer; using {DEFAULT_MAX_EVICTIONS}")
                max_evictions = DEFAULT_MAX_EVICTIONS
        self.max_evictions = int(max_evictions)
        self.evictions = []     # [{"slot", "suspect", "kind", "detail"}]

    @property
    def blocked_slots(self):
        """Hostfile slots an integrity verdict has indicted — excluded
        from every subsequent spawn."""
        return frozenset(e["slot"] for e in self.evictions
                         if e["slot"] is not None)

    def filter_slots(self, slots):
        """``slots`` minus the blocklist, order preserved."""
        blocked = self.blocked_slots
        return [s for s in slots if s not in blocked]

    def record(self, suspect, slot, kind, detail=""):
        """Note one consumed verdict.  Returns True when the eviction
        fits the budget (resize around the suspect); False when this is
        a *repeated eviction* and the run must poison — there is no
        longer a basis to trust that evicting one more host fixes the
        fleet."""
        self.evictions.append({"slot": slot, "suspect": int(suspect),
                               "kind": str(kind), "detail": str(detail)})
        within = len(self.evictions) <= self.max_evictions
        if within:
            logger.warning(
                "integrity eviction %d/%d: rank %s (slot %s) indicted "
                "by %s verdict; its devices leave the elastic budget",
                len(self.evictions), self.max_evictions, suspect, slot,
                kind)
        else:
            logger.error(
                "repeated integrity eviction (%d > budget %d): rank %s "
                "(slot %s, %s) indicted after a previous eviction "
                "already resized around a suspect — poisoning the run "
                "instead of shrinking further",
                len(self.evictions), self.max_evictions, suspect, slot,
                kind)
        return within
