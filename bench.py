#!/usr/bin/env python
"""Benchmark: BERT-large pretraining throughput on one TPU chip.

Mirrors the reference's headline single-GPU number — BERT-large seq128
samples/sec (272 samples/s on V100-32GB, ``BASELINE.md``).  Runs the full
DeepSpeed-TPU engine train step (fwd + bwd + fused Adam) in bf16 with flash
attention on the available accelerator and prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SAMPLES_PER_SEC = 272.0  # V100-32GB, reference fastest-bert post
SEQ = 128
VOCAB = 30528


def main():
    import jax

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
    from deepspeed_tpu.parallel import make_mesh

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))

    dev = jax.devices()[0]
    mesh = make_mesh({"data": 1}, devices=[dev])

    config = {
        "train_batch_size": batch,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
    }
    model = BertForPreTrainingTPU(
        BertConfig.bert_large(max_position_embeddings=512, vocab_size=VOCAB,
                              hidden_dropout_prob=0.0,
                              attention_probs_dropout_prob=0.0),
        compute_dtype=None)
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, VOCAB, size=(batch, SEQ)).astype(np.int32)
    b = {
        "input_ids": ids,
        "attention_mask": np.ones((batch, SEQ), np.int32),
        "token_type_ids": np.zeros((batch, SEQ), np.int32),
        "masked_lm_labels": np.where(rng.random((batch, SEQ)) < 0.15, ids,
                                     -100).astype(np.int32),
        "next_sentence_labels": rng.integers(0, 2, size=(batch,)).astype(np.int32),
    }

    def one_step():
        loss = engine.train_batch(iter([b]))
        return loss

    for _ in range(max(warmup, 1)):
        loss = one_step()
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = batch * steps / dt
    print(json.dumps({
        "metric": "bert_large_seq128_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
