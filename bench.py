#!/usr/bin/env python
"""Benchmark: BERT-large pretraining throughput on one TPU chip.

Mirrors the reference's headline single-GPU number — BERT-large seq128
samples/sec (272 samples/s on V100-32GB, ``BASELINE.md``).  Runs the full
DeepSpeed-TPU engine train step (fwd + bwd + fused Adam) in bf16 on the
available accelerator and prints ONE JSON line.  Attention dispatch is the
engine's memory-aware policy (XLA batched attention at this seq length;
the Pallas flash kernel takes over when score memory exceeds its budget).

Timing discipline: on this platform ``jax.block_until_ready`` has been
observed not to fence remote execution, so every timing boundary is a host
round-trip — ``jax.device_get`` of the loss scalar — which cannot complete
until the whole step has executed.  The run is sanity-checked against the
chip's physical peak: model-FLOPs utilisation (MFU) above 100% means the
harness measured nothing, and the benchmark hard-fails rather than report
an impossible number.
"""

import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SAMPLES_PER_SEC = 272.0  # V100-32GB, reference fastest-bert post
BASELINE_SEQ512_SAMPLES_PER_SEC = 52.0  # same post, seq 512 row
SEQ = 128
VOCAB = 30528

# Chip peak table + MFU math live in deepspeed_tpu/profiling/utilization.py
# (ONE implementation shared with the flops profiler and the capacity
# planner, so utilisation numbers cannot drift between reporters);
# imported lazily below — bench defers every deepspeed_tpu/jax import
# until after the compile cache is configured.


def bert_model_flops_per_sample(cfg, seq):
    """Analytic fwd+bwd model FLOPs per sample (2x for matmul, 3x total with
    backward), mirroring the accounting of the reference flops profiler
    (``deepspeed/profiling/flops_profiler/profiler.py``).  When the MLM
    head gathers labeled positions (``max_predictions_per_seq``), the head
    term counts only the gathered positions — the FLOPs actually executed —
    so MFU stays honest as the model gets cheaper."""
    h, i, L, v = (cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_hidden_layers, cfg.vocab_size)

    def layer_flops(q_len):
        """One encoder layer with q_len query/output positions (kv = seq)."""
        return (
            2 * q_len * h * h + 2 * seq * h * 2 * h  # Q proj + KV proj
            + 2 * q_len * seq * h * 2                # scores + context
            + 2 * q_len * h * h                      # attn out
            + 2 * q_len * h * i * 2                  # FC1 + FC2
        )

    n_pred = min(cfg.max_predictions_per_seq or seq, seq)
    # with the gather head, the FINAL layer computes only the n_pred label
    # positions + CLS (queries gathered; kv full) — count what executes
    n_last = seq if n_pred == seq else n_pred + 1
    head = 2 * n_pred * h * h + 2 * n_pred * h * v  # MLM transform + vocab proj
    fwd = (L - 1) * layer_flops(seq) + layer_flops(n_last) + head
    return 3 * fwd  # bwd ~= 2x fwd


def gpt2_model_flops_per_sample(cfg, seq):
    """GPT-2 fwd+bwd model FLOPs per sample.  The causal flash kernel skips
    upper-triangle score blocks, so attention score/context FLOPs count at
    half the dense matmul — the FLOPs actually executed."""
    h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    per_layer = (
        2 * seq * h * 3 * h            # QKV
        + 2 * seq * seq * h * 2 // 2   # scores + context (causal half)
        + 2 * seq * h * h              # attn out
        + 2 * seq * h * 4 * h * 2      # FC1 + FC2
    )
    head = 2 * seq * h * v  # tied LM head over every position
    return 3 * (L * per_layer + head)


def exact_count_mlm_labels(rng, ids, n_pred):
    """Labels with EXACTLY n_pred masked positions per row — the bing_bert
    data contract the gather head assumes (max_predictions_per_seq)."""
    b, s = ids.shape
    labels = np.full((b, s), -100, np.int32)
    for r in range(b):
        pos = rng.permutation(s)[:n_pred]
        labels[r, pos] = ids[r, pos]
    return labels


def memory_receipts(record, engine, prefix=None):
    """Memory receipts for one bench row (fail-soft): the compiled
    train-step program's predicted temp bytes (ledger), the live HBM
    peak watermark summed over local devices, and — offload rows — the
    pinned-host buffer bytes.  Registered in ``tools/bench_schema.py``
    as ``*_peak_hbm_bytes`` / ``*_predicted_temp_bytes`` /
    ``*_host_buffer_bytes``."""
    try:
        from deepspeed_tpu.profiling.memory import device_memory_summary

        tag = (lambda f: f"{prefix}_{f}") if prefix else (lambda f: f)
        # training engines compile "train_step"; serving engines
        # (examples/bench_serving.py rides the same helper) compile the
        # paged decode program instead
        temps = engine.memory_ledger.predicted_temp_bytes("train_step")
        if temps is None:
            from deepspeed_tpu.profiling.comm import SERVE_DECODE_PROGRAM
            temps = engine.memory_ledger.predicted_temp_bytes(
                SERVE_DECODE_PROGRAM)
        if temps is not None:
            record[tag("predicted_temp_bytes")] = int(temps)
        summary = device_memory_summary()
        if summary["reporting"]:
            record[tag("peak_hbm_bytes")] = int(
                summary["peak_bytes_in_use"])
        host_bytes = engine.memory_ledger.host_buffers.total_bytes()
        if prefix and host_bytes:
            record[tag("host_buffer_bytes")] = int(host_bytes)
    except Exception as e:  # pragma: no cover - receipts never gate rows
        print(f"bench: memory receipts unavailable: {e!r:.200}",
              file=sys.stderr)


def comm_receipts(record, engine, prefix=None):
    """Communication receipts for one bench row (fail-soft): the
    compiled step program's collective count and predicted wire bytes
    from the comm ledger's compile-time HLO walk
    (``profiling/comm.py``).  A dp=1 single-chip row legitimately
    records 0 collectives — the receipt proves it, instead of leaving
    "no cross-chip traffic" as an assumption."""
    try:
        tag = (lambda f: f"{prefix}_{f}") if prefix else (lambda f: f)
        receipt = engine.comm_receipt()
        if receipt is not None:
            record[tag("comm_collectives_per_step")] = int(
                receipt["collectives"])
        wire = engine.comm_wire_bytes_per_step()
        if wire is not None:
            record[tag("comm_wire_bytes_per_step")] = int(wire)
        # overlap receipts (round 11, profiling/overlap): how much of
        # the predicted wire the compiled schedules actually expose as
        # step latency — the metric the overlapped-streaming work must
        # drive down, with bench_diff gating regressions
        ov = engine.overlap_receipt()
        if ov is not None:
            record[tag("exposed_wire_seconds")] = float(
                ov["exposed_wire_seconds"])
            record[tag("overlap_fraction")] = float(
                ov["overlap_fraction"])
    except Exception as e:  # pragma: no cover - receipts never gate rows
        print(f"bench: comm receipts unavailable: {e!r:.200}",
              file=sys.stderr)


def attribution_receipts(record, engine, prefix=None):
    """Step-time attribution receipts for one bench row (fail-soft):
    the reconciled budget's predicted step seconds and — once steps
    have run — the unexplained fraction of the measured p50
    (``profiling/attribution.py``; the doctor CLI replays the same
    reconciliation from the run artifacts)."""
    try:
        tag = (lambda f: f"{prefix}_{f}") if prefix else (lambda f: f)
        rec = engine.attribution_receipt()
        if rec is None:
            return
        record[tag("predicted_step_seconds")] = float(
            rec["predicted_step_seconds"])
        if rec["step_unexplained_fraction"] is not None:
            record[tag("step_unexplained_fraction")] = float(
                rec["step_unexplained_fraction"])
        check = rec.get("flops_check")
        if check and check.get("disagrees"):
            factor = ("" if check.get("ratio") is None
                      else f"x{check['ratio']:.1f} ")
            print(f"bench: attribution flops cross-check disagrees "
                  f"{factor}(jaxpr "
                  f"{check['flops_compute_seconds']:.6f}s vs roofline "
                  f"{check['roofline_compute_seconds']:.6f}s)",
                  file=sys.stderr)
    except Exception as e:  # pragma: no cover - receipts never gate rows
        print(f"bench: attribution receipts unavailable: {e!r:.200}",
              file=sys.stderr)


def dsp_receipts(record, engine, prefix=None):
    """Program-verification receipt for one bench row (fail-soft): the
    unsuppressed DSP6xx violation count over every compiled engine
    program (donation aliases materialized, collectives on the right
    mesh axes — ``tools/dslint/programs.py``).  Pinned at 0; the
    ``bench_diff`` gate treats any increase as a regression."""
    try:
        tag = (lambda f: f"{prefix}_{f}") if prefix else (lambda f: f)
        report = engine.verify_programs()
        if report is None:
            return
        # the gated field carries ERROR-severity findings only: the
        # heuristic DSP warnings (psum-for-pmean suspects, ledger
        # drift) have no ratchet on the bench surface, so they report
        # via the ungated dsp_warnings field + stderr instead of
        # hard-failing bench_diff (same rationale as the planner's
        # exit code)
        record[tag("dsp_violations")] = int(report["errors"])
        # per-device parameter residency (profiling/sharding, DSS8xx):
        # the compiled step's materialized ÷shard receipt, lower-is-
        # better gated in bench_schema — the bench half of ROADMAP
        # item 2's parameter-memory ÷ dp criterion
        sharding = report.get("sharding") or {}
        pb = (sharding.get("train_step") or {}).get(
            "param_bytes_per_device")
        if pb is not None:
            record[tag("param_bytes_per_device")] = int(pb)
        warnings = int(report["violations"]) - int(report["errors"])
        if not prefix and warnings:
            record["dsp_warnings"] = warnings
        if not prefix and report["downgraded"]:
            record["dsp_downgraded"] = int(report["downgraded"])
        for d in report["diagnostics"]:
            if not d.suppressed:
                print(f"bench: dsp finding: {d.format()}", file=sys.stderr)
    except Exception as e:  # pragma: no cover - receipts never gate rows
        print(f"bench: dsp receipts unavailable: {e!r:.200}",
              file=sys.stderr)


def main():
    import jax

    # Persistent compile cache (runtime/compilation): the big offload
    # programs (gpt2-xl with host gradients compiles ~35 min on the
    # tunneled toolchain) are byte-identical across runs — warm runs
    # skip straight to execution.  CompileStats records the cold (miss
    # compile) vs warm (hit retrieval) wall split into the bench JSON.
    from deepspeed_tpu.runtime.compilation import (CompileStats,
                                                   DeepSpeedCompilationConfig,
                                                   configure_persistent_cache)

    cache_dir = configure_persistent_cache(DeepSpeedCompilationConfig(
        {"compilation": {"cache": True, "cache_dir": os.environ.get(
            "BENCH_CACHE_DIR", os.path.join(
                os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))}}))
    compile_stats = CompileStats()

    import deepspeed_tpu as deepspeed
    from deepspeed_tpu.models import BertConfig, BertForPreTrainingTPU
    from deepspeed_tpu.parallel import make_mesh
    from deepspeed_tpu.profiling.utilization import (
        achieved_tflops, chip_peak_tflops, model_flops_utilization)

    batch = int(os.environ.get("BENCH_BATCH", "112"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    # The reference's 272 samples/s is real pretraining — dropout 0.1 on.
    # Benchmark the same workload (rbg PRNG + byte-mask dropout keep the
    # cost ~7%); BENCH_DROPOUT=0 ablates.
    dropout_p = float(os.environ.get("BENCH_DROPOUT", "0.1"))

    dev = jax.devices()[0]
    mesh = make_mesh({"data": 1}, devices=[dev])

    # The block-sparse kernel row runs FIRST, sole-tenant: its ms-scale
    # kernel timings are the most co-residency-sensitive measurement in
    # the bench (measured 2.38x with the engines' executables resident vs
    # 3.09x clean — allocator pressure inflates both dense and sparse,
    # sparse more).  Engine rows keep the conservative co-resident
    # methodology.
    sparse_record = {}
    try:
        _measure_sparse_attention(sparse_record)
    except Exception as e:  # pragma: no cover - depends on chip
        sparse_record["sparse_attn_exc"] = f"sparse run failed: {e!r:.300}"
    try:
        jax.clear_caches()
    except Exception:
        pass

    config = {
        "train_batch_size": batch,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        # compiled-program memory + comm ledgers: predicted_temp_bytes /
        # peak_hbm_bytes / comm_wire_bytes_per_step receipts ride the
        # bench JSON (zero step cost — both record at compile time)
        "profiling": {"memory_ledger": True, "comm_ledger": True},
    }
    # 20 = bing_bert's max_predictions_per_seq at seq 128; the MLM head
    # gathers these positions before the vocab projection (~8% of step
    # FLOPs saved vs projecting all 128)
    n_pred = int(os.environ.get("BENCH_MAX_PRED", "20"))
    bert_cfg = BertConfig.bert_large(max_position_embeddings=512, vocab_size=VOCAB,
                                     hidden_dropout_prob=dropout_p,
                                     attention_probs_dropout_prob=dropout_p,
                                     max_predictions_per_seq=n_pred or None)
    model = BertForPreTrainingTPU(bert_cfg, compute_dtype=None)
    engine, *_ = deepspeed.initialize(model=model, config=config, mesh=mesh)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, VOCAB, size=(batch, SEQ)).astype(np.int32)
    b = {
        "input_ids": ids,
        "attention_mask": np.ones((batch, SEQ), np.int32),
        "token_type_ids": np.zeros((batch, SEQ), np.int32),
        "masked_lm_labels": exact_count_mlm_labels(rng, ids, n_pred or
                                                   int(SEQ * 0.15)),
        "next_sentence_labels": rng.integers(0, 2, size=(batch,)).astype(np.int32),
    }

    def one_step():
        return engine.train_batch(iter([b]))

    for _ in range(max(warmup, 1)):
        loss = one_step()
    # Host round-trip: guarantees all queued work has finished.
    float(jax.device_get(loss))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    final_loss = float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    samples_per_sec = batch * steps / dt
    model_flops = bert_model_flops_per_sample(bert_cfg, SEQ)
    tflops = achieved_tflops(samples_per_sec, model_flops)
    peak = chip_peak_tflops(dev)
    mfu = model_flops_utilization(samples_per_sec, model_flops, peak)

    if not math.isfinite(final_loss):
        print(json.dumps({"metric": "bert_large_seq128_samples_per_sec_per_chip",
                          "value": 0.0, "unit": "samples/s", "vs_baseline": 0.0,
                          "error": f"non-finite loss {final_loss}"}))
        sys.exit(1)
    if mfu > 1.0:
        print(json.dumps({"metric": "bert_large_seq128_samples_per_sec_per_chip",
                          "value": 0.0, "unit": "samples/s", "vs_baseline": 0.0,
                          "error": (f"measured {tflops:.0f} TFLOP/s exceeds chip "
                                    f"peak {peak:.0f} — timing harness did not "
                                    f"synchronize; result discarded")}))
        sys.exit(1)

    record = {
        "metric": "bert_large_seq128_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 3),
        "model_tflops_per_sec": round(tflops, 1),
        "mfu": round(mfu, 4),
        "chip_peak_tflops": peak,
        "loss": round(final_loss, 4),
        "batch": batch,
        "dropout": dropout_p,
        "device": getattr(dev, "device_kind", str(dev)),
    }

    # memory + comm receipts for the primary row: predicted temp bytes
    # from the compiled train step + the live peak watermark
    # (profiling/memory), and the step program's collective receipt
    # (profiling/comm — 0 collectives on this dp=1 chip, proven not
    # assumed)
    memory_receipts(record, engine)
    comm_receipts(record, engine)
    attribution_receipts(record, engine)
    dsp_receipts(record, engine)

    # HBM discipline: each engine holds ~5 GB of master+optimizer state for
    # these model sizes; three co-resident engines exhaust a 16 GB chip.
    # Free the primary before the secondaries run.
    import gc

    del engine, model, b
    gc.collect()

    # Secondary: the reference's seq-512 row (52 samples/s on V100).  The
    # flash kernel (tuned blocks + in-kernel PRNG dropout) carries this
    # config; BENCH_SEQ512=0 skips.  Guarded so a secondary failure (OOM on
    # a smaller chip, compile error) can never lose the validated primary
    # metric above.  One retry: this environment's remote compile service
    # sporadically 500s.  (Round-4 negative result: running secondaries in
    # fresh subprocesses measured gpt2 at 7 samples/s and seq512 at 82 —
    # the parent's live runtime starves the child of HBM — so co-resident
    # measurement stays, costing gpt2 a known ~6% vs sole-tenant runs.)
    seq512_fallback = 1
    for attempt in (1, 2):
        try:
            _measure_seq512(record, deepspeed, BertConfig,
                            BertForPreTrainingTPU, mesh, config, rng, steps,
                            warmup, dropout_p, peak, attempt=seq512_fallback)
            record.pop("seq512_exc", None)
            break
        except Exception as e:  # pragma: no cover - depends on chip
            record["seq512_exc"] = f"secondary run failed (try {attempt}): {e!r:.300}"
            # drop to the smaller batch only on memory failures; a
            # transient compile-service 500 retries the SAME batch
            if "RESOURCE_EXHAUSTED" in repr(e) or "emory" in repr(e):
                seq512_fallback += 1
            gc.collect()

    # Tertiary: a causal-LM row (3 of the 5 BASELINE configs are GPT-2
    # class).  GPT-2-medium 355M, seq 1024, the BASELINE #3 shape: ZeRO
    # stage 2 + Lamb + bf16 (degenerate but real at dp=1).  (Order A/B:
    # gpt2-first gains it 1.6% but costs seq512 4% — seq512 runs first.)
    # Drop the finished rows' compiled executables before measuring: each
    # earlier engine's programs pin HBM scratch that fragments the
    # allocator (the measured ~6% in-bench vs sole-tenant gap); every
    # remaining row compiles its own programs anyway.
    try:
        import jax

        jax.clear_caches()
    except Exception:
        pass
    gc.collect()
    for attempt in (1, 2):
        try:
            _measure_gpt2(record, deepspeed, mesh, rng, steps, warmup,
                          dropout_p, peak)
            record.pop("gpt2_exc", None)
            break
        except Exception as e:  # pragma: no cover - depends on chip
            record["gpt2_exc"] = f"gpt2 run failed (try {attempt}): {e!r:.300}"
            gc.collect()

    # Quaternary: block-sparse attention kernel vs dense flash at seq 16k
    # (the reference's sparse-attention SPEED claim, measured on-chip
    # every round instead of living in PERF.md prose).  Measured FIRST
    # in main(), sole-tenant (see the note there); merged here.
    record.update(sparse_record)

    # Quinary: ZeRO-Offload step-time tax (the reference's ZeRO-Offload
    # capability, ZeRO-Offload.md:10).  GPT-2-large: the LARGEST config
    # this chip trains at all — device-resident just fits, offload pays
    # the host-streaming tax (the capacity ladder with max-size search is
    # examples/bench_offload_capacity.py; too slow for the driver run).
    try:
        import jax

        jax.clear_caches()
    except Exception:
        pass
    gc.collect()
    for attempt in (1, 2):
        try:
            _measure_offload(record, deepspeed, mesh, rng)
            record.pop("offload_exc", None)
            break
        except Exception as e:  # pragma: no cover - depends on chip
            record["offload_exc"] = f"offload run failed (try {attempt}): {e!r:.300}"
            gc.collect()

    # Senary: GPT-2-xl with offload_gradients — the capacity headline.
    # Own guard (so a failure cannot re-run or lose the gpt2-large row
    # above) with one retry: the remote compile service sporadically
    # 500s, and the persistent cache makes the retry cheap.
    for attempt in (1, 2):
        try:
            _measure_offload_xl(record, deepspeed, mesh, rng)
            record.pop("offload_xl_exc", None)
            break
        except Exception as e:  # pragma: no cover - depends on chip
            record["offload_xl_exc"] = f"xl run failed (try {attempt}): {e!r:.300}"
            gc.collect()

    # Septenary: ZeRO-2 bucketed gradient-collective overlap A/B
    # (overlap_comm on vs off) through a fresh-subprocess harness on a
    # dp mesh — dryrun-marked (virtual CPU mesh, toy geometry) off the
    # attachment.  Guarded like every secondary row.
    for attempt in (1, 2):
        try:
            _measure_zero2_overlap(record)
            record.pop("zero2_overlap_exc", None)
            break
        except Exception as e:  # pragma: no cover - depends on chip
            record["zero2_overlap_exc"] = (
                f"zero2 overlap A/B failed (try {attempt}): {e!r:.300}")
            gc.collect()

    # Compile-time receipts for the whole bench process: cold = backend
    # compile wall actually paid (cache misses), warm = persistent-cache
    # retrieval wall for hits.  A rerun against a populated cache shows
    # compile_seconds_cold ~ 0 — the warm-start claim, measured.
    record.update(compile_stats.as_dict())
    record["compile_cache_dir"] = cache_dir

    # schema check (deepspeed_tpu/tools/bench_schema.py): fail-soft —
    # drift is reported on stderr, the measured record always prints
    from deepspeed_tpu.tools.bench_schema import validate_record

    for problem in validate_record(record):
        print(f"bench-schema: {problem}", file=sys.stderr)

    print(json.dumps(record))



def _measure_offload(record, deepspeed, mesh, rng):
    """GPT-2-large ZeRO-Offload step time, fp32 host state THEN the
    reduced-precision bf16 row (``offload_state_dtype: "bf16"`` —
    stochastic-rounding write-back, half the state wire bytes).  Both
    rows record ``host_state_dtype`` and ``host_state_bytes_per_step``
    so the halved-wire claim is auditable from the JSON alone."""
    if os.environ.get("BENCH_OFFLOAD", "1") == "0":
        return
    import gc

    import jax

    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    steps = int(os.environ.get("BENCH_OFFLOAD_STEPS", "5"))
    cfg = GPT2Config(hidden_size=1280, num_layers=36, num_heads=20,
                     max_position_embeddings=1024, embd_dropout=0.0,
                     attn_dropout=0.0, resid_dropout=0.0, remat=True,
                     loss_chunk=256)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(4, 1024)).astype(np.int32)}

    def one_row(prefix, state_dtype):
        zero = {"stage": 2, "cpu_offload": True}
        if state_dtype is not None:
            zero["offload_state_dtype"] = state_dtype
        model = GPT2LMHeadTPU(cfg)
        engine, *_ = deepspeed.initialize(
            model=model, mesh=mesh,
            config={"train_batch_size": 4, "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "zero_optimization": zero,
                    "profiling": {"memory_ledger": True,
                                  "comm_ledger": True},
                    "bf16": {"enabled": True}})
        for _ in range(2):
            loss = engine.train_batch(iter([batch]))
        v = float(jax.device_get(loss))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(iter([batch]))
        v = float(jax.device_get(loss))
        dt = (time.perf_counter() - t0) / steps
        if math.isfinite(v):
            record[f"{prefix}_ms_per_step"] = round(dt * 1e3, 0)
            record[f"{prefix}_params_b"] = 0.77
            record[f"{prefix}_host_state_dtype"] = engine.host_state_dtype()
            record[f"{prefix}_host_state_bytes_per_step"] = int(
                engine.host_state_bytes_per_step())
            memory_receipts(record, engine, prefix=prefix)
            comm_receipts(record, engine, prefix=prefix)
            attribution_receipts(record, engine, prefix=prefix)
            dsp_receipts(record, engine, prefix=prefix)
        else:
            record[f"{prefix}_error"] = f"non-finite loss {v}"
        del engine, model
        gc.collect()

    one_row("offload_gpt2_large", None)
    if os.environ.get("BENCH_OFFLOAD_BF16", "1") != "0":
        try:
            jax.clear_caches()
        except Exception:
            pass
        one_row("offload_gpt2_large_bf16", "bf16")


def _measure_offload_xl(record, deepspeed, mesh, rng):
    """GPT-2-xl (1.56B): beyond anything the chip can hold
    device-resident (1.5B fp32 grads alone would be 6 GB + 3 GB bf16
    params).  Runs the full capacity configuration: host
    master/optimizer AND host gradients (offload_gradients), host-side
    init.  Separate from the gpt2-large leg so a failure here cannot
    re-run (or lose) that row.

    DEFAULT-ON since round 6 (BENCH_OFFLOAD_XL=0 skips): the row used
    to be opt-in because its first compile was ~35 min of unrolled
    chunk programs — with the uniform-chunk scan update the program no
    longer scales with chunk count, and the persistent compile cache
    makes every rerun warm regardless (compile_seconds_cold/_warm in
    this JSON are the receipt)."""
    if os.environ.get("BENCH_OFFLOAD_XL", "1") == "0":
        record["offload_xl_note"] = "skipped (BENCH_OFFLOAD_XL=0)"
        return
    import jax

    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    steps = int(os.environ.get("BENCH_OFFLOAD_STEPS", "5"))
    cfg = GPT2Config(hidden_size=1600, num_layers=48, num_heads=25,
                     max_position_embeddings=1024, embd_dropout=0.0,
                     attn_dropout=0.0, resid_dropout=0.0, remat=True,
                     loss_chunk=256)
    model = GPT2LMHeadTPU(cfg)
    zero = {"stage": 2, "cpu_offload": True, "offload_gradients": True}
    # host-group layout is AUTO-DERIVED since round 6 (buffer-count cap,
    # zero/coordinator.py): this row runs with an EMPTY offload_group_mb
    # override — the round-5 manual 3584 foot-gun retired to an env
    # escape hatch
    if os.environ.get("BENCH_XL_GROUP_MB"):
        zero["offload_group_mb"] = int(os.environ["BENCH_XL_GROUP_MB"])
    engine, *_ = deepspeed.initialize(
        model=model, mesh=mesh,
        config={"train_batch_size": 4, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "zero_optimization": zero,
                "profiling": {"memory_ledger": True,
                              "comm_ledger": True},
                "bf16": {"enabled": True}})
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(4, 1024)).astype(np.int32)}
    for _ in range(2):
        loss = engine.train_batch(iter([batch]))
    v = float(jax.device_get(loss))
    t0 = time.perf_counter()
    xl_steps = max(steps - 2, 3)
    for _ in range(xl_steps):
        loss = engine.train_batch(iter([batch]))
    v = float(jax.device_get(loss))
    dt = (time.perf_counter() - t0) / xl_steps
    if math.isfinite(v):
        record["offload_gpt2_xl_ms_per_step"] = round(dt * 1e3, 0)
        record["offload_gpt2_xl_params_b"] = 1.56
        record["offload_gpt2_xl_host_state_dtype"] = \
            engine.host_state_dtype()
        record["offload_gpt2_xl_host_state_bytes_per_step"] = int(
            engine.host_state_bytes_per_step())
        record["offload_gpt2_xl_host_groups"] = len(
            engine.flat.host_group_bounds or ((0, 0),))
        memory_receipts(record, engine, prefix="offload_gpt2_xl")
        comm_receipts(record, engine, prefix="offload_gpt2_xl")
        attribution_receipts(record, engine, prefix="offload_gpt2_xl")
        dsp_receipts(record, engine, prefix="offload_gpt2_xl")
    else:
        record["offload_xl_error"] = f"non-finite loss {v}"
    del engine, model


# Fresh-subprocess trial for the zero-2 overlap A/B: bench rows run
# co-resident, but the A/B needs a dp>1 MESH — on a single-chip bench
# host that means a virtual CPU mesh, which must not contaminate the
# parent's live backend.  The child prints ONE "Z2AB {json}" line.
_Z2AB_TRIAL = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["Z2AB_REPO"])
import numpy as np, jax
import deepspeed_tpu as deepspeed
from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU
from deepspeed_tpu.parallel import make_mesh

overlap = os.environ["Z2AB_OVERLAP"] == "1"
dp = int(os.environ["Z2AB_DP"])
steps = int(os.environ.get("Z2AB_STEPS", "5"))
cfg = GPT2Config(vocab_size=256, hidden_size=int(os.environ.get(
    "Z2AB_HIDDEN", "128")), num_layers=2, num_heads=4,
    max_position_embeddings=64, embd_dropout=0.0, attn_dropout=0.0,
    resid_dropout=0.0)
mesh = make_mesh({"data": dp}, devices=jax.devices()[:dp])
engine, *_ = deepspeed.initialize(
    model=GPT2LMHeadTPU(cfg), mesh=mesh,
    config={"train_batch_size": 2 * dp, "steps_per_print": 10 ** 9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 2, "overlap_comm": overlap,
                                  "reduce_bucket_size": 40000,
                                  "allgather_bucket_size": 80000},
            "profiling": {"comm_ledger": True, "memory_ledger": True}})
assert engine.comm_overlap_enabled() == overlap
rng = np.random.default_rng(0)
batch = {"input_ids": rng.integers(0, 256, size=(2 * dp, 64)).astype(
    np.int32)}
for _ in range(2):
    loss = engine.train_batch(iter([batch]))
float(jax.device_get(loss))
t0 = time.perf_counter()
for _ in range(steps):
    loss = engine.train_batch(iter([batch]))
v = float(jax.device_get(loss))
dt = (time.perf_counter() - t0) / steps
out = {"ms_per_step": dt * 1e3, "loss": v}
ov = engine.overlap_receipt()
if ov is not None:
    out["exposed_wire_seconds"] = ov["exposed_wire_seconds"]
    out["overlap_fraction"] = ov["overlap_fraction"]
sched = engine.collective_schedule() or {}
out["buckets"] = sched.get("rs_buckets", 0)
print("Z2AB " + json.dumps(out), flush=True)
"""


def _measure_zero2_overlap(record):
    """ZeRO-2 overlap_comm A/B row: the bucketed (overlapped) exchange
    vs the GSPMD fused control, each in a FRESH subprocess (the dp mesh
    must not contaminate the parent's single-chip engines; compiled
    executables share the parent's persistent cache).  On a non-TPU or
    single-device backend the children run a virtual CPU mesh and the
    row is dryrun-marked — the harness executes end-to-end, the bench
    attachment supplies the milliseconds."""
    if os.environ.get("BENCH_ZERO2_OVERLAP", "1") == "0":
        record["zero2_overlap_note"] = "skipped (BENCH_ZERO2_OVERLAP=0)"
        return
    import subprocess

    import jax

    n_real = jax.device_count()
    platform = jax.devices()[0].platform
    dryrun = platform != "tpu" or n_real < 2
    dp = n_real if not dryrun else 4
    env = dict(os.environ)
    env["Z2AB_REPO"] = os.path.dirname(os.path.abspath(__file__))
    env["Z2AB_DP"] = str(dp)
    if dryrun:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + f" --xla_force_host_platform_device_count={dp}").strip()
        record["zero2_overlap_note"] = (
            f"dryrun: non-TPU/single-chip backend, toy geometry on a "
            f"virtual {dp}-device CPU mesh")
    record["zero2_overlap_dp"] = dp
    rows = {}
    for tag, ov in (("overlap", "1"), ("serial", "0")):
        env["Z2AB_OVERLAP"] = ov
        proc = subprocess.run([sys.executable, "-u", "-c", _Z2AB_TRIAL],
                              env=env, capture_output=True, text=True,
                              timeout=int(os.environ.get(
                                  "BENCH_Z2AB_TIMEOUT", "1200")))
        line = next((ln[len("Z2AB "):] for ln
                     in proc.stdout.splitlines()[::-1]
                     if ln.startswith("Z2AB ")), None)
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"zero2 A/B child ({tag}) rc={proc.returncode}: "
                f"{proc.stderr[-300:]}")
        rows[tag] = json.loads(line)
        print(f"bench: zero2[{tag}] {rows[tag]['ms_per_step']:.1f} "
              f"ms/step exposed="
              f"{rows[tag].get('exposed_wire_seconds')}", file=sys.stderr)
    record["zero2_overlap_ms_per_step"] = round(
        rows["overlap"]["ms_per_step"], 2)
    record["zero2_serial_ms_per_step"] = round(
        rows["serial"]["ms_per_step"], 2)
    record["zero2_overlap_buckets"] = int(rows["overlap"]["buckets"])
    if "exposed_wire_seconds" in rows["overlap"]:
        record["zero2_overlap_exposed_wire_seconds"] = float(
            rows["overlap"]["exposed_wire_seconds"])
        record["zero2_overlap_fraction"] = float(
            rows["overlap"]["overlap_fraction"])
    if "exposed_wire_seconds" in rows["serial"]:
        record["zero2_serial_exposed_wire_seconds"] = float(
            rows["serial"]["exposed_wire_seconds"])


def _measure_sparse_attention(record):
    if os.environ.get("BENCH_SPARSE", "1") == "0":
        return
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_sparse_attention",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples",
                     "bench_sparse_attention.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, flash_block_sparse_attention)
    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    s = int(os.environ.get("BENCH_SPARSE_SEQ", "16384"))
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (1, s, mod.H, mod.D), jnp.bfloat16)
               for kk in ks)
    layout = BigBirdSparsityConfig(
        num_heads=mod.H, block=512, num_random_blocks=1,
        num_sliding_window_blocks=3, num_global_blocks=1).make_layout(s)
    # interleaved min-of-repeats (PERF.md methodology): the round-5
    # driver row timed each kernel ONCE and read 2.65x where the
    # example bench (warmed by its earlier seq points) read 3.09x —
    # single shots swing ±50% on this attachment and the driver's
    # fresh-process dense shot ate the cold-device wobble
    t_dense, t_sparse = mod.timed_min_interleaved([
        mod.make_runner(lambda a, b_, c: flash_attention(a, b_, c),
                        q, k, v, 6),
        mod.make_runner(
            lambda a, b_, c: flash_block_sparse_attention(a, b_, c, layout),
            q, k, v, 6)])
    record["sparse_attn_repeats"] = mod.REPEATS
    record["sparse_attn_seq"] = s
    record["sparse_attn_dense_ms"] = round(t_dense * 1e3, 2)
    record["sparse_attn_sparse_ms"] = round(t_sparse * 1e3, 2)
    record["sparse_attn_speedup_vs_dense"] = round(t_dense / t_sparse, 2)


def _measure_gpt2(record, deepspeed, mesh, rng, steps, warmup, dropout_p,
                  peak):
    import jax

    from deepspeed_tpu.models import GPT2Config, GPT2LMHeadTPU

    if os.environ.get("BENCH_GPT2", "1") == "0":
        return
    bg = int(os.environ.get("BENCH_GPT2_BATCH", "8"))
    seq = 1024
    g_steps = max(steps // 3, 5)
    cfg = GPT2Config(hidden_size=1024, num_layers=24, num_heads=16,
                     max_position_embeddings=seq,
                     embd_dropout=dropout_p, attn_dropout=dropout_p,
                     resid_dropout=dropout_p)
    model = GPT2LMHeadTPU(cfg, compute_dtype=None)
    engine, *_ = deepspeed.initialize(
        model=model, mesh=mesh,
        config={"train_batch_size": bg, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Lamb", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 2},
                "bf16": {"enabled": True}})
    ids = rng.integers(0, cfg.vocab_size, size=(bg, seq)).astype(np.int32)
    batch = {"input_ids": ids}
    for _ in range(max(warmup // 2, 1)):
        loss = engine.train_batch(iter([batch]))
    float(jax.device_get(loss))
    t0 = time.perf_counter()
    for _ in range(g_steps):
        loss = engine.train_batch(iter([batch]))
    final = float(jax.device_get(loss))
    dt = time.perf_counter() - t0
    from deepspeed_tpu.profiling.utilization import model_flops_utilization

    sps = bg * g_steps / dt
    mfu = model_flops_utilization(sps, gpt2_model_flops_per_sample(cfg, seq),
                                  peak)
    if mfu > 1.0 or not math.isfinite(final):
        record["gpt2_error"] = f"invalid measurement: mfu={mfu:.2f} loss={final}"
    else:
        record["gpt2_medium_seq1024_samples_per_sec"] = round(sps, 2)
        record["gpt2_medium_tokens_per_sec"] = round(sps * seq, 0)
        record["gpt2_mfu"] = round(mfu, 4)
        record["gpt2_batch"] = bg
    del engine, model


def _measure_seq512(record, deepspeed, BertConfig, BertForPreTrainingTPU,
                    mesh, config, rng, steps, warmup, dropout_p, peak,
                    attempt=1):
    import jax

    if os.environ.get("BENCH_SEQ512", "1") != "0":
        # batch 32 beats 16 here (93.6 vs 91 co-resident; 99.5 sole-
        # tenant, examples/bench_seq512_dispatch.py) but may OOM next to
        # the primary engine on smaller chips — the retry attempt indexes
        # a fallback list, and the batch used is recorded in the JSON so a
        # downgraded retry (e.g. after a transient compile 500) is visible
        choices = [int(os.environ["BENCH_SEQ512_BATCH"])] \
            if os.environ.get("BENCH_SEQ512_BATCH") else [32, 16]
        b512 = choices[min(attempt - 1, len(choices) - 1)]
        s512_steps = max(steps // 3, 5)
        # 80 = bing_bert's max_predictions_per_seq at seq 512
        cfg512 = BertConfig.bert_large(
            max_position_embeddings=512, vocab_size=VOCAB,
            hidden_dropout_prob=dropout_p,
            attention_probs_dropout_prob=dropout_p,
            max_predictions_per_seq=80)
        model512 = BertForPreTrainingTPU(cfg512, compute_dtype=None)
        eng512, *_ = deepspeed.initialize(
            model=model512, config=dict(config, train_batch_size=b512),
            mesh=mesh)
        ids512 = rng.integers(0, VOCAB, size=(b512, 512)).astype(np.int32)
        batch512 = {
            "input_ids": ids512,
            "attention_mask": np.ones((b512, 512), np.int32),
            "token_type_ids": np.zeros((b512, 512), np.int32),
            "masked_lm_labels": exact_count_mlm_labels(rng, ids512, 80),
            "next_sentence_labels": rng.integers(
                0, 2, size=(b512,)).astype(np.int32),
        }
        for _ in range(max(warmup // 2, 1)):
            loss512 = eng512.train_batch(iter([batch512]))
        float(jax.device_get(loss512))
        t0 = time.perf_counter()
        for _ in range(s512_steps):
            loss512 = eng512.train_batch(iter([batch512]))
        final512 = float(jax.device_get(loss512))
        dt512 = time.perf_counter() - t0
        from deepspeed_tpu.profiling.utilization import \
            model_flops_utilization

        sps512 = b512 * s512_steps / dt512
        mfu512 = model_flops_utilization(
            sps512, bert_model_flops_per_sample(cfg512, 512), peak)
        if mfu512 > 1.0 or not math.isfinite(final512):
            # same discipline as the primary metric: an unsynchronized or
            # NaN measurement is reported as invalid, not silently omitted
            record["seq512_error"] = (
                f"invalid measurement: mfu={mfu512:.2f} loss={final512}")
        else:
            record["seq512_batch"] = b512
            record["seq512_samples_per_sec"] = round(sps512, 2)
            record["seq512_vs_baseline"] = round(
                sps512 / BASELINE_SEQ512_SAMPLES_PER_SEC, 3)
            record["seq512_mfu"] = round(mfu512, 4)
        del eng512, model512


if __name__ == "__main__":
    main()
